"""Broadcast-group data-plane tests: rolling-join fan-out tree, peer
serving, store-offload guarantee, dead-peer fallback (reference coverage
model: tests/test_gpu_store.py broadcast groups — here host-staged,
SURVEY.md §3.5 / §7 hard-part 3)."""

import os
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from kubetorch_tpu import BroadcastWindow
from kubetorch_tpu.data_store.http_store import HttpStoreBackend


@pytest.fixture()
def store(tmp_path, monkeypatch):
    root = tmp_path / "store-root"
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {**os.environ, "KT_STORE_ROOT": str(root)}
    proc = subprocess.Popen(
        [sys.executable, "-m", "kubetorch_tpu.data_store.store_server",
         "--host", "127.0.0.1", "--port", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    url = f"http://127.0.0.1:{port}"
    import httpx

    for _ in range(100):
        try:
            if httpx.get(f"{url}/health", timeout=2.0).status_code == 200:
                break
        except httpx.HTTPError:
            time.sleep(0.2)
    else:
        proc.kill()
        raise RuntimeError("store server did not start")

    # Isolate the peer cache + peer-server singleton per test.
    import kubetorch_tpu.data_store.broadcast as bcast

    monkeypatch.setattr(bcast, "_CACHE_ROOT", tmp_path / "peer-cache")
    monkeypatch.setattr(bcast.PeerServer, "_instances", {})
    yield url
    proc.terminate()
    proc.wait(5)


@pytest.mark.level("minimal")
def test_blob_broadcast_tree_offloads_store(store):
    backend = HttpStoreBackend(store)
    payload = os.urandom(256 * 1024)
    backend.put_blob("bcast/weights.bin", payload)

    world = 6
    window = BroadcastWindow(world_size=world, fanout=2, timeout=60)
    results = [None] * world
    errors = []

    def worker(i):
        try:
            be = HttpStoreBackend(store)
            results[i] = be.get_blob("bcast/weights.bin", broadcast=window)
        except Exception as exc:  # noqa: BLE001 - surfaced via errors list
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(90)
    assert not errors, errors
    assert all(r == payload for r in results)

    status = backend.bcast_status(window.resolved_group("bcast/weights.bin"))
    assert status["complete"] is True
    assert status["counts"]["complete"] == world
    # The store never serves more than `fanout` members concurrently, and
    # once peers complete they absorb later joiners — so a meaningful share
    # of the group must have fetched from peers, not the store.
    assert status["store_children"] <= world - 2


@pytest.mark.level("minimal")
def test_reput_never_serves_stale_peer_bytes(store, tmp_path):
    """A peer advertised at JOIN time still holds the previous put's bytes
    in its cache; children of the new round's group must get the NEW
    content (version-scoped .bv cache names)."""
    backend = HttpStoreBackend(store)
    world = 4

    def fan_out(expect):
        results = [None] * world
        errors = []

        def worker(i):
            try:
                window = BroadcastWindow(
                    world_size=world, fanout=1, timeout=60,
                    cache_root=str(tmp_path / f"peer{i}"))
                be = HttpStoreBackend(store)
                results[i] = be.get_blob("bcast/reput.bin",
                                         broadcast=window)
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(90)
        assert not errors, errors
        assert all(bytes(r) == expect for r in results)

    round1 = os.urandom(128 * 1024)
    backend.put_blob("bcast/reput.bin", round1)
    fan_out(round1)

    round2 = os.urandom(128 * 1024)
    backend.put_blob("bcast/reput.bin", round2)
    fan_out(round2)


@pytest.mark.level("minimal")
def test_tree_broadcast_roundtrip(store, tmp_path):
    backend = HttpStoreBackend(store)
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "sub" / "a.txt").write_text("alpha")
    (src / "b.txt").write_text("beta")
    backend.put_path("bcast/tree", src)

    window = BroadcastWindow(world_size=2, fanout=1, timeout=60)
    dests = [tmp_path / "d0", tmp_path / "d1"]
    errors = []

    def worker(i):
        try:
            HttpStoreBackend(store).get_path(
                "bcast/tree", dests[i], broadcast=window)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(90)
    assert not errors, errors
    for dest in dests:
        assert (dest / "sub" / "a.txt").read_text() == "alpha"
        assert (dest / "b.txt").read_text() == "beta"


@pytest.mark.level("minimal")
def test_dead_peer_falls_back_to_store(store):
    backend = HttpStoreBackend(store)
    payload = b"fallback-bytes"
    backend.put_blob("bcast/fb.bin", payload)

    group = "fb-group"
    # Simulate a member that fetched and then died: it completed advertising
    # a serve_url nobody listens on. Peers are preferred over the store, so
    # the next joiner is assigned the dead peer and must fall back.
    backend.bcast_join(group, key="bcast/fb.bin", member_id="ghost",
                       world_size=2, fanout=1)
    backend.bcast_complete(group, "ghost",
                           serve_url="http://127.0.0.1:9/")  # dead port

    window = BroadcastWindow(world_size=2, fanout=1, timeout=30,
                             group_id=group, serve=False)
    got = backend.get_blob("bcast/fb.bin", broadcast=window)
    assert got == payload
    status = backend.bcast_status(group)
    assert status["counts"]["complete"] == 2  # ghost + the real member


@pytest.mark.level("minimal")
def test_reput_invalidates_group(store):
    """Re-broadcasting a re-put key must serve the NEW bytes — the RL
    weight-sync loop re-puts the same key every iteration."""
    backend = HttpStoreBackend(store)
    backend.put_blob("bcast/iter.bin", b"round-1 " * 100)
    w1 = BroadcastWindow(world_size=1, fanout=1, timeout=30)
    assert backend.get_blob("bcast/iter.bin",
                            broadcast=w1).startswith(b"round-1")

    time.sleep(0.05)  # mtime tick
    backend.put_blob("bcast/iter.bin", b"round-2 " * 100)
    w2 = BroadcastWindow(world_size=1, fanout=1, timeout=30)
    got = backend.get_blob("bcast/iter.bin", broadcast=w2)
    assert got.startswith(b"round-2")
    # Fresh group state: exactly one completed member, not two rounds' worth.
    status = backend.bcast_status(w2.resolved_group("bcast/iter.bin"))
    assert status["counts"] == {"complete": 1}


@pytest.mark.level("minimal")
def test_lease_reclaims_crashed_fetcher(store):
    """A member that takes a slot and dies must not wedge the group."""
    backend = HttpStoreBackend(store)
    backend.put_blob("bcast/lease.bin", b"x" * 64)
    group = "lease-group"
    crasher = backend.bcast_join(group, key="bcast/lease.bin",
                                 member_id="crasher", world_size=2,
                                 fanout=1, lease=10)  # server floor is 10s
    assert crasher["parent"] == ""  # holds the store's only slot
    waiter = backend.bcast_join(group, key="bcast/lease.bin",
                                member_id="waiter", world_size=2,
                                fanout=1, lease=10)
    assert waiter["status"] == "joined"  # saturated
    time.sleep(10.5)
    waiter = backend.bcast_member(group, "waiter")
    assert waiter["status"] == "fetching" and waiter["parent"] == ""


@pytest.mark.level("unit")
def test_auto_block_k_divisibility():
    from kubetorch_tpu.ops.flash_attention import auto_block_k

    assert auto_block_k(2048) == 1024
    assert auto_block_k(1536) == 512   # 1024 doesn't divide, 512 does
    assert auto_block_k(768) == 512    # neither divides → capped fallback
    assert auto_block_k(128) == 128
    assert auto_block_k(2048, requested=256) == 256


@pytest.mark.level("unit")
def test_window_group_derivation():
    w = BroadcastWindow(world_size=4)
    assert w.resolved_group("a/b/c") == "bcast-a-b-c"
    assert BroadcastWindow(world_size=4, group_id="g").resolved_group("x") == "g"


@pytest.mark.level("minimal")
def test_get_arrays_broadcast(store, monkeypatch):
    import numpy as np

    from kubetorch_tpu.data_store import device_transfer as dt
    from kubetorch_tpu.data_store.client import DataStoreClient

    monkeypatch.setenv("KT_STORE_URL", store)
    DataStoreClient._default = None
    tree = {"w": np.arange(8, dtype=np.float32),
            "b": np.ones((2, 2), dtype=np.float32)}
    dt.put_arrays("bcast/params", tree)
    window = BroadcastWindow(world_size=1, fanout=1, timeout=30, serve=False)
    out = dt.get_arrays("bcast/params", template=tree, broadcast=window)
    np.testing.assert_array_equal(np.asarray(out["w"]), tree["w"])
    DataStoreClient._default = None


@pytest.mark.level("unit")
def test_sweep_stale_trees(tmp_path):
    """Superseded versions get a tombstone, then a grace window, then the
    disk back; referenced versions and fresh tmp- stages are untouched."""
    from kubetorch_tpu.data_store.broadcast import _sweep_stale_trees

    cache = tmp_path / "cache"
    trees = cache / ".trees"
    trees.mkdir(parents=True)
    live = trees / "aaaa"
    old = trees / "bbbb"
    tmp = trees / "tmp-cccc"
    for d in (live, old, tmp):
        d.mkdir()
        (d / "f.bin").write_bytes(b"x")
    (cache / "key").symlink_to(live)

    _sweep_stale_trees(cache, grace=60.0)
    assert live.is_dir() and tmp.is_dir()
    assert old.is_dir()  # grace window: still serving in-flight requests
    tomb = trees / "bbbb.tombstone"
    assert tomb.exists() and not (trees / "aaaa.tombstone").exists()

    # age the tombstone past grace → reclaimed; live + fresh tmp survive
    os.utime(tomb, (time.time() - 120, time.time() - 120))
    _sweep_stale_trees(cache, grace=60.0)
    assert not old.exists() and not tomb.exists()
    assert live.is_dir() and tmp.is_dir()

    # orphaned crashed-fetcher stage goes once past tmp_grace
    os.utime(tmp, (time.time() - 7200, time.time() - 7200))
    _sweep_stale_trees(cache, grace=60.0, tmp_grace=3600.0)
    assert not tmp.exists()


@pytest.mark.level("minimal")
def test_straggler_completion_does_not_register_stale_source(store):
    """A member that finishes fetching OLD bytes after a re-put must not
    re-register its copy as a P2P source — get_source consumers would be
    routed to last round's weights for up to the 1h TTL."""
    backend = HttpStoreBackend(store)
    backend.put_blob("w/x", b"v1" * 100)
    backend.bcast_join("g1", key="w/x", member_id="m1", world_size=2,
                       fanout=2)
    backend.put_blob("w/x", b"v2" * 100)   # re-put while m1 is fetching
    backend.bcast_complete("g1", "m1", serve_url="http://10.1.1.1:1")
    s = backend.get_source("w/x")
    assert s["peer"] is False, f"stale straggler registered: {s}"

    # a fresh group against the current bytes still registers fine
    backend.bcast_join("g2", key="w/x", member_id="m2", world_size=1,
                       fanout=2)
    backend.bcast_complete("g2", "m2", serve_url="http://10.1.1.2:1")
    s = backend.get_source("w/x")
    assert s["peer"] is True and s["source"] == "http://10.1.1.2:1"


@pytest.mark.level("minimal")
def test_completed_peer_serves_plain_key(store, tmp_path):
    """ADVICE r3 (medium): bcast_complete registers peers as P2P sources
    for the PLAIN key, so a /sources consumer fetching /blob/{key} from
    the peer must be served — the peer publishes its version-scoped cache
    file under the plain name at completion."""
    import httpx

    backend = HttpStoreBackend(store)
    payload = os.urandom(64 * 1024)
    backend.put_blob("bcast/plain.bin", payload)
    window = BroadcastWindow(world_size=1, fanout=2, timeout=30,
                             cache_root=str(tmp_path / "peer0"))
    got = backend.get_blob("bcast/plain.bin", broadcast=window)
    assert bytes(got) == payload

    src = backend.get_source("bcast/plain.bin")
    assert src["peer"] is True, src
    resp = httpx.get(f"{src['source']}/blob/bcast/plain.bin", timeout=10)
    assert resp.status_code == 200
    assert resp.content == payload


@pytest.mark.level("minimal")
def test_plain_get_polls_inflight_peer_cache(tmp_path):
    """ADVICE r3: a plain GET against a serving cache mid-fetch gets 202
    (progress JSON) — get_blob must poll until the blob is published, not
    hand the JSON back as blob bytes."""
    from kubetorch_tpu.data_store.broadcast import PeerServer

    root = tmp_path / "cache"
    (root / "w").mkdir(parents=True)
    payload = os.urandom(32 * 1024)
    final = root / "w" / "x.bin"
    part = final.with_name("x.bin.part-123-abc")
    part.write_bytes(payload[: len(payload) // 2])
    part.with_name(part.name + ".size").write_text(str(len(payload)))
    (final.with_name("x.bin.part")).symlink_to(part.name)

    peer = PeerServer.ensure(root)
    assert peer is not None
    backend = HttpStoreBackend(f"http://127.0.0.1:{peer.port}")

    def publish():
        time.sleep(0.5)
        # atomic, like the production path (.part → os.replace): a plain
        # write_bytes can be observed half-written by the poller
        staged = final.with_name("x.bin.staged")
        staged.write_bytes(payload)
        import os as _os

        _os.replace(staged, final)
        final.with_name("x.bin.part").unlink()

    t = threading.Thread(target=publish)
    t.start()
    got = backend.get_blob("w/x.bin")
    t.join()
    assert bytes(got) == payload


@pytest.mark.level("minimal")
def test_store_version_header_aborts_raced_fetch(store, tmp_path):
    """ADVICE r3: the store stamps blob GETs with X-KT-Blob-Version; a
    broadcast member caching under a join-time .bv name must abort when
    the store's content has moved on (re-put racing the fetch)."""
    from kubetorch_tpu.exceptions import DataStoreError
    from kubetorch_tpu.data_store.broadcast import _stream_blob_into_cache

    backend = HttpStoreBackend(store)
    backend.put_blob("w/raced.bin", b"v1" * 1000)   # version 1
    cache = tmp_path / "cache"
    local = _stream_blob_into_cache(
        backend, "w/raced.bin", cache,
        cache_name="w/raced.bin.bv1", expect_version=1)
    assert local.read_bytes() == b"v1" * 1000

    backend.put_blob("w/raced.bin", b"v2" * 1000)   # version 2
    with pytest.raises(DataStoreError, match="changed mid-broadcast"):
        _stream_blob_into_cache(
            backend, "w/raced.bin", cache,
            cache_name="w/raced.bin.bv1b", expect_version=1)
