"""kt.app end-to-end: health-gated readiness, /http proxy, crash surfacing.

Reference: resources/compute/app.py:20 (health_path) + app status handling
in serving/http_server.py:1700 — an App pod is ready only when its own
health endpoint answers, and an exited app surfaces through /ready.
"""

import os
import sys
import time
from pathlib import Path

import pytest

import kubetorch_tpu as kt
from kubetorch_tpu.exceptions import StartupError

ASSETS = Path(__file__).parent / "assets" / "miniapp"


@pytest.fixture(autouse=True, scope="module")
def _local_state(tmp_path_factory):
    state = tmp_path_factory.mktemp("ktlocal-app")
    os.environ["KT_LOCAL_STATE"] = str(state)
    import kubetorch_tpu.provisioning.backend as backend

    backend._LOCAL_ROOT = state
    yield
    for record in backend.LocalBackend().list_services():
        backend.LocalBackend().teardown(record["service_name"], quiet=True)


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.level("minimal")
def test_app_health_gated_readiness_and_proxy(monkeypatch):
    """Deploy a real HTTP server that binds its port only after a delay:
    .to() must block until the app's own /healthz answers, so the very
    first /http proxy call succeeds — no ready-before-alive race."""
    monkeypatch.setenv("KT_TEST_APP_DELAY", "1.5")
    port = _free_port()
    app = kt.app(
        command=f"{sys.executable} {ASSETS / 'app_server.py'} {port}",
        name="miniapp", port=port, health_path="/healthz",
        root_path=str(ASSETS))
    t0 = time.monotonic()
    app.to(kt.Compute(cpus="0.1"))
    launch_s = time.monotonic() - t0
    try:
        # readiness waited out the bind delay
        assert launch_s >= 1.5, f"ready before the app bound ({launch_s}s)"
        # first proxied request works immediately — that's the point
        out = app.request("/greet")
        assert out["hello"] == "from-miniapp"
        status = app.status()
        assert status["running"] is True
    finally:
        app.teardown()


@pytest.mark.level("minimal")
def test_app_crash_fails_launch_fast():
    """An app that exits before passing its health check must fail .to()
    quickly with the exit code — not burn the whole launch timeout."""
    port = _free_port()
    app = kt.app(
        command=f"{sys.executable} -c 'import sys; sys.exit(3)'",
        name="miniapp-crash", port=port, health_path="/healthz",
        root_path=str(ASSETS))
    t0 = time.monotonic()
    with pytest.raises(StartupError, match="exited with code 3"):
        app.to(kt.Compute(cpus="0.1", launch_timeout=60))
    assert time.monotonic() - t0 < 30, "burned the launch timeout"
