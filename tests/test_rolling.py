"""Continuous-batching tests: greedy equivalence with isolated Generator
runs, mid-flight admission, slot reuse, compile stability (no reference
analogue — vLLM-core scheduling owned natively, see models/rolling.py)."""

import jax
import numpy as np
import pytest

from kubetorch_tpu.models import LlamaConfig, llama
from kubetorch_tpu.models.generate import Generator
from kubetorch_tpu.models.rolling import RollingGenerator, _bucket


def _cfg():
    return LlamaConfig(vocab_size=256, embed_dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, head_dim=16, mlp_dim=128, remat=False,
                       dtype="float32", param_dtype="float32",
                       max_seq_len=128)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    params = llama.init(jax.random.key(0), cfg)
    return params, cfg


@pytest.mark.level("unit")
def test_bucket():
    assert _bucket(3) == 16
    assert _bucket(16) == 16
    assert _bucket(17) == 32
    assert _bucket(100) == 128


@pytest.mark.level("minimal")
def test_rolling_greedy_matches_isolated_generator(model):
    """Tokens from the shared rolling batch must equal each prompt's
    isolated greedy generation — the correctness bar for continuous
    batching."""
    params, cfg = model
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [11, 22, 33, 44, 55, 66, 7]]
    n_new = 12

    gen = Generator(params, cfg)
    isolated = [gen.generate([p], max_new_tokens=n_new, temperature=0.0,
                             seed=0)[0] for p in prompts]

    eng = RollingGenerator(params, cfg, max_slots=4)
    rids = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
    out = eng.run()
    for rid, expect in zip(rids, isolated):
        assert out[rid] == expect, (rid, out[rid], expect)


@pytest.mark.level("minimal")
def test_midflight_admission_and_slot_reuse(model):
    """A request arriving mid-decode joins without disturbing running
    sequences; freed slots are reused; short requests finish first."""
    params, cfg = model
    gen = Generator(params, cfg)
    pa, pb, pc = [1, 2, 3], [4, 5, 6, 7], [10, 20]
    iso = {
        "a": gen.generate([pa], max_new_tokens=10, temperature=0.0)[0],
        "b": gen.generate([pb], max_new_tokens=4, temperature=0.0)[0],
        "c": gen.generate([pc], max_new_tokens=6, temperature=0.0)[0],
    }

    eng = RollingGenerator(params, cfg, max_slots=2)  # forces queueing
    ra = eng.submit(pa, max_new_tokens=10)
    rb = eng.submit(pb, max_new_tokens=4)
    rc = eng.submit(pc, max_new_tokens=6)  # queued until a slot frees

    seen = {ra: [], rb: [], rc: []}
    steps = 0
    while eng.pending:
        for rid, toks, done in eng.step():
            seen[rid].extend(toks)
        steps += 1
        assert steps < 100
    assert seen[ra] == iso["a"]
    assert seen[rb] == iso["b"]
    assert seen[rc] == iso["c"]
    # b (4 tokens) freed its slot for c while a (10 tokens) kept running
    assert len(eng._free) == eng.max_slots


@pytest.mark.level("minimal")
def test_eos_frees_slot(model):
    params, cfg = model
    eng = RollingGenerator(params, cfg, max_slots=2, eos_id=0)
    rid = eng.submit([1, 2, 3], max_new_tokens=50)
    out = eng.run()
    toks = out[rid]
    # either hit eos (ends with 0) or ran to the cap
    assert len(toks) <= 50
    if 0 in toks:
        assert toks[-1] == 0 and toks.count(0) == 1


@pytest.mark.level("minimal")
def test_rolling_service_concurrent_callers(model):
    """Threaded callers (the kt.cls pod-server execution model) share one
    batch and each gets its own isolated-generation-equivalent result."""
    import threading

    from kubetorch_tpu.models.rolling import RollingService

    params, cfg = model
    gen = Generator(params, cfg)
    prompts = [[1, 2, 3], [4, 5, 6, 7], [10, 20], [8, 9]]
    iso = [gen.generate([p], max_new_tokens=6, temperature=0.0)[0]
           for p in prompts]

    svc = RollingService(RollingGenerator(params, cfg, max_slots=2))
    results = [None] * len(prompts)
    errors = []

    def call(i):
        try:
            results[i] = svc.generate(prompts[i], max_new_tokens=6,
                                      timeout=120)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=call, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(150)
    assert not errors, errors
    assert results == iso


@pytest.mark.level("minimal")
def test_rolling_under_tp_mesh(model):
    """Continuous batching on a sharded model: tp=2 mesh over the virtual
    8-device farm, params placed by logical axes, same greedy tokens."""
    import jax as _jax

    from kubetorch_tpu.models import llama as _llama
    from kubetorch_tpu.parallel import MeshSpec, use_mesh
    from kubetorch_tpu.parallel.sharding import (
        ShardingRules,
        named_sharding,
    )

    params, cfg = model
    mesh = MeshSpec(tp=2).build(_jax.devices()[:2])
    rules = ShardingRules.default()
    axes = _llama.param_logical_axes(cfg)
    shardings = _jax.tree.map(
        lambda ax: named_sharding(mesh, rules, *ax), axes,
        is_leaf=lambda x: isinstance(x, tuple))
    sharded = _jax.tree.map(_jax.device_put, params, shardings)
    prompts = [[1, 2, 3, 4], [7, 8]]
    gen = Generator(params, cfg)
    iso = [gen.generate([p], max_new_tokens=6, temperature=0.0)[0]
           for p in prompts]
    eng = RollingGenerator(sharded, cfg, max_slots=2, mesh=mesh,
                           rules=rules)
    rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
    out = eng.run()
    for rid, expect in zip(rids, iso):
        assert out[rid] == expect


@pytest.mark.level("minimal")
def test_prefix_caching_matches_full_prompt(model):
    """register_prefix + suffix submits must produce the same greedy
    tokens as isolated generation over the concatenated prompt — including
    the prefix-pad garbage edge (prefix 5 pads to 16; 1-token suffix)."""
    params, cfg = model
    gen = Generator(params, cfg)
    prefix = [11, 12, 13, 14, 15]           # pads to bucket 16 → garbage gap
    suffixes = [[21, 22, 23], [31], [41, 42, 43, 44, 45, 46, 47]]
    iso = [gen.generate([prefix + s], max_new_tokens=8,
                        temperature=0.0)[0] for s in suffixes]

    eng = RollingGenerator(params, cfg, max_slots=4)
    pid = eng.register_prefix(prefix)
    rids = [eng.submit(s, max_new_tokens=8, prefix_id=pid)
            for s in suffixes]
    out = eng.run()
    for rid, expect in zip(rids, iso):
        assert out[rid] == expect, (rid, out[rid], expect)
    # mixed traffic: un-prefixed requests still work alongside
    plain = eng.submit([1, 2, 3], max_new_tokens=4)
    mixed = eng.submit(suffixes[0], max_new_tokens=4, prefix_id=pid)
    out2 = eng.run()
    assert out2[plain] == gen.generate([[1, 2, 3]], max_new_tokens=4,
                                       temperature=0.0)[0]
    assert out2[mixed] == iso[0][:4]


@pytest.mark.level("minimal")
def test_stop_sequences(model):
    """Generation halts when a stop sequence appears, including stop
    sequences that span a chunk boundary."""
    params, cfg = model
    gen = Generator(params, cfg)
    prompt = [1, 2, 3]
    free = gen.generate([prompt], max_new_tokens=20, temperature=0.0)[0]
    assert len(free) == 20
    # choose a stop seq from the greedy continuation spanning positions
    # 5..7 — i.e. crossing the steps_per_call=6 chunk boundary
    stop_seq = free[5:8]

    def earliest_end(tokens, seq):
        for end in range(len(seq), len(tokens) + 1):
            if tokens[end - len(seq):end] == seq:
                return end
        return None

    eng = RollingGenerator(params, cfg, max_slots=2, steps_per_call=6)
    rid = eng.submit(prompt, max_new_tokens=20, stop=[stop_seq])
    out = eng.run()[rid]
    # cut right after the EARLIEST completion of the stop sequence (greedy
    # continuations repeat tokens, so it may complete before position 8)
    assert out == free[:earliest_end(free, stop_seq)]
    # un-matched stop sequences don't interfere
    rid2 = eng.submit(prompt, max_new_tokens=10, stop=[[99999 % cfg.vocab_size,
                                                        1234 % cfg.vocab_size]])
    assert eng.run()[rid2] == free[:10]


@pytest.mark.level("minimal")
def test_repetition_penalty_reduces_repeats(model):
    """Greedy decode of this tiny random model degenerates into repeats;
    a repetition penalty must break the loop (and penalty=1.0 must stay
    exactly equal to the un-penalized path — covered by the equivalence
    tests running through the same code)."""
    params, cfg = model
    prompt = [1, 2, 3]
    eng = RollingGenerator(params, cfg, max_slots=2)
    rid0 = eng.submit(prompt, max_new_tokens=24)
    base = eng.run()[rid0]
    rid1 = eng.submit(prompt, max_new_tokens=24, repetition_penalty=1.5)
    pen = eng.run()[rid1]

    def repeats(seq):
        return sum(1 for a, b in zip(seq, seq[1:]) if a == b)

    assert pen != base
    assert repeats(pen) < repeats(base), (repeats(pen), repeats(base))


@pytest.mark.level("minimal")
def test_prefill_bucket_compile_stability(model):
    """Prompts in the same bucket reuse one prefill compile."""
    params, cfg = model
    eng = RollingGenerator(params, cfg, max_slots=4)
    for p in ([1, 2], [3, 4, 5], [6] * 10, [7] * 16):  # all bucket ≤16
        eng.submit(p, max_new_tokens=2)
    eng.run()
    # jit cache: one entry per distinct p_pad bucket
    sizes = eng._prefill._cache_size()
    assert sizes == 1, sizes


@pytest.mark.level("minimal")
def test_admit_width_chunked_admission_parity(model):
    """admit_width < arrivals splits admission into several narrow
    prefill calls (the 8B serving layout: 112 slots, width-16 prefills);
    tokens must still match unchunked greedy admission exactly."""
    params, cfg = model
    prompts = [[i + 1, i + 2, i + 3] for i in range(6)]
    n_new = 8

    wide = RollingGenerator(params, cfg, max_slots=8)
    rids_w = [wide.submit(p, max_new_tokens=n_new) for p in prompts]
    expect = wide.run()

    narrow = RollingGenerator(params, cfg, max_slots=8, admit_width=2)
    rids_n = [narrow.submit(p, max_new_tokens=n_new) for p in prompts]
    got = narrow.run()
    for rw, rn in zip(rids_w, rids_n):
        assert got[rn] == expect[rw], (rn, got[rn], expect[rw])


@pytest.mark.level("minimal")
def test_long_prefix_bucket_overshoot_clamps_to_grid(model):
    """A prefix whose BUCKET plus the suffix bucket exceeds max_len (the
    real tokens fit) must still admit — the prefixed own-cache clamps to
    the grid width instead of splicing a wider block (r4 review find)."""
    params, cfg = model
    max_len = 80
    eng = RollingGenerator(params, cfg, max_slots=2, max_len=max_len,
                           steps_per_call=2)
    prefix = [(i % 200) + 1 for i in range(40)]   # buckets to 64
    pid = eng.register_prefix(prefix)
    # suffix buckets to 16; 64 + 16 = 80 == max_len here, and with a
    # 33-token prefix bucket overshoot is exercised via a second engine
    rid = eng.submit([5, 6, 7], max_new_tokens=8, prefix_id=pid)
    out = eng.run()
    assert len(out[rid]) == 8

    gen = Generator(params, cfg)
    expect = gen.generate([prefix + [5, 6, 7]], max_new_tokens=8,
                          temperature=0.0)[0]
    assert out[rid] == expect


@pytest.mark.level("unit")
def test_int8_grid_rolling_matches_bf16_rolling(model):
    """kv_dtype='int8' rolling decode: same engine semantics at half the
    grid bytes. Near-ties aside, greedy tokens agree with the bf16 grid
    (same bar as the static Generator's int8-KV test)."""
    from kubetorch_tpu.models.rolling import RollingGenerator

    params, cfg = model
    prompts = [[3, 7, 11, 2], [5, 1], [9, 9, 9, 9, 9, 9]]
    outs = {}
    for kvd in ("bf16", "int8"):
        eng = RollingGenerator(params, cfg, max_slots=4, steps_per_call=4,
                               kv_dtype=kvd)
        rids = [eng.submit(p, max_new_tokens=12) for p in prompts]
        res = eng.run()
        outs[kvd] = [res[r] for r in rids]
    assert all(len(o) == 12 for o in outs["int8"])
    # Quantization noise on a 2-layer/256-vocab toy flips near-tie argmaxes
    # and every flip diverges the rest of that row, so full-horizon
    # identity is not the contract. What is: the first chunk (before any
    # divergence can compound) agrees, and overall agreement stays high
    # (deterministic inputs — this is a regression pin, not a coin flip).
    first_chunk = sum(a == b for x, y in zip(outs["bf16"], outs["int8"])
                      for a, b in zip(x[:4], y[:4]))
    assert first_chunk >= 11, (first_chunk, outs)
    total = sum(len(o) for o in outs["bf16"])
    agree = sum(a == b for x, y in zip(outs["bf16"], outs["int8"])
                for a, b in zip(x, y))
    assert agree >= int(0.7 * total), (agree, total, outs)


@pytest.mark.level("minimal")
def test_int8_grid_prefix_matches_full_prompt(model):
    """Shared prefixes compose with the int8 serving grid: the prefix
    fills a QUANTIZED private cache at registration, so spliced rows are
    bit-identical to a full-prompt int8 admission — greedy outputs
    match exactly (same engine, no cross-dtype near-tie caveat)."""
    import jax.numpy as jnp

    from kubetorch_tpu.models.rolling import RollingGenerator

    params, cfg = model
    prefix = [11, 12, 13, 14, 15]
    suffixes = [[21, 22, 23], [31], [41, 42, 43, 44, 45, 46, 47]]

    full = RollingGenerator(params, cfg, max_slots=4, kv_dtype="int8",
                            admit_width=1)
    assert full.cache["k"].dtype == jnp.int8 and "ks" in full.cache
    rid_f = [full.submit(prefix + s, max_new_tokens=8) for s in suffixes]
    out_f = full.run()

    eng = RollingGenerator(params, cfg, max_slots=4, kv_dtype="int8",
                           admit_width=1)
    pid = eng.register_prefix(prefix)
    assert eng._prefixes[pid]["planes"]["k"].dtype == jnp.int8
    rid_p = [eng.submit(s, max_new_tokens=8, prefix_id=pid)
             for s in suffixes]
    out_p = eng.run()
    got = [out_p[r] for r in rid_p]
    want = [out_f[r] for r in rid_f]
    # full-prompt admission buckets prefix+suffix together while the
    # prefixed path buckets only the suffix — different einsum widths can
    # flip near-tie argmaxes on this toy model, so hold the same
    # agreement bar as the int8-vs-bf16 test rather than bit identity
    total = sum(len(o) for o in want)
    agree = sum(a == b for x, y in zip(want, got) for a, b in zip(x, y))
    assert agree >= int(0.7 * total), (agree, total, want, got)
    first_chunk = sum(a == b for x, y in zip(want, got)
                      for a, b in zip(x[:4], y[:4]))
    assert first_chunk >= 11, (first_chunk, want, got)


@pytest.mark.level("minimal")
def test_prefix_with_adapter_matches_merged_model(model):
    """A prefix registered under adapter i + adapted suffix decode must
    equal generation with that adapter merged into the weights."""
    import jax.numpy as jnp

    from kubetorch_tpu.models import lora as lora_mod
    from kubetorch_tpu.models.lora import LoraConfig, stack_adapters
    from kubetorch_tpu.models.rolling import RollingGenerator

    params, cfg = model
    lcfg = LoraConfig(rank=4, alpha=8.0)
    ad = lora_mod.init(jax.random.key(3), params, lcfg)
    for name in ad:
        ad[name]["b"] = (jax.random.normal(
            jax.random.key(11), ad[name]["b"].shape,
            jnp.float32) * 0.2).astype(ad[name]["b"].dtype)
    stacked = stack_adapters([ad], lcfg)
    prefix = [11, 12, 13, 14, 15]
    suffix = [21, 22, 23]

    merged = lora_mod.merge(params, ad, lcfg)
    ref_eng = RollingGenerator(merged, cfg, max_slots=2)
    rpid = ref_eng.register_prefix(prefix)
    rr = ref_eng.submit(suffix, max_new_tokens=8, prefix_id=rpid)
    want = ref_eng.run()[rr]

    eng = RollingGenerator(params, cfg, max_slots=2, adapters=stacked,
                           adapter_scale=lcfg.scale)
    pid = eng.register_prefix(prefix, adapter_id=0)
    r = eng.submit(suffix, max_new_tokens=8, prefix_id=pid, adapter_id=0)
    got = eng.run()[r]
    assert got == want, (got, want)


@pytest.mark.level("unit")
def test_kv_dtype_validated(model):
    from kubetorch_tpu.models.rolling import RollingGenerator

    params, cfg = model
    with pytest.raises(ValueError, match="kv_dtype"):
        RollingGenerator(params, cfg, max_slots=2, kv_dtype="fp8")


# --------------------------------------------------------------- spec


@pytest.mark.level("minimal")
def test_spec_rolling_matches_plain_rolling(model):
    """Speculative continuous batching (spec_k>1) must be greedy
    token-identical to the plain engine — drafts only survive where they
    equal the model's own argmax, so the emitted stream is the same."""
    params, cfg = model
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [11, 22, 33, 44, 55, 66, 7]]
    n_new = 12

    plain = RollingGenerator(params, cfg, max_slots=4, steps_per_call=4)
    rid_p = [plain.submit(p, max_new_tokens=n_new) for p in prompts]
    out_p = plain.run()

    spec = RollingGenerator(params, cfg, max_slots=4, steps_per_call=2,
                            spec_k=4)
    rid_s = [spec.submit(p, max_new_tokens=n_new) for p in prompts]
    out_s = spec.run()
    for rp, rs in zip(rid_p, rid_s):
        assert out_p[rp] == out_s[rs], (out_p[rp], out_s[rs])
    stats = spec.spec_stats
    # device-side acceptance count includes the surplus tokens trimmed
    # at each request's budget boundary, so >= the delivered total
    assert stats["emitted"] >= 3 * n_new
    assert stats["tokens_per_pass"] >= 1.0


@pytest.mark.level("minimal")
def test_spec_rolling_midflight_admission(model):
    """Requests joining an in-flight speculative batch decode correctly
    and reuse freed slots (the continuous-batching contract, spec on)."""
    params, cfg = model
    plain = RollingGenerator(params, cfg, max_slots=2, steps_per_call=4)
    spec = RollingGenerator(params, cfg, max_slots=2, steps_per_call=2,
                            spec_k=4)
    outs = {}
    for name, eng in (("plain", plain), ("spec", spec)):
        acc = {}
        r1 = eng.submit([1, 2, 3], max_new_tokens=6)
        r2 = eng.submit([4, 5], max_new_tokens=10)
        for rid, toks, _ in eng.step():
            acc.setdefault(rid, []).extend(toks)
        # arrives mid-flight; max_slots=2 so it queues until r1 frees
        r3 = eng.submit([6, 7, 8, 9], max_new_tokens=6)
        for rid, toks in eng.run().items():
            acc.setdefault(rid, []).extend(toks)
        outs[name] = [acc[r] for r in (r1, r2, r3)]
    assert outs["plain"] == outs["spec"], outs


@pytest.mark.level("minimal")
def test_spec_rolling_repetitive_accepts_multiple(model):
    """A looping continuation must clear >1.5 tokens per verify pass —
    the regime the speculative engine exists for."""
    params, cfg = model
    gen = Generator(params, cfg)
    warm = gen.generate([[5, 9, 13]], max_new_tokens=32,
                        temperature=0.0)[0]
    prompt = [5, 9, 13] + warm[:24]

    plain = RollingGenerator(params, cfg, max_slots=2, steps_per_call=4)
    rp = plain.submit(prompt, max_new_tokens=24)
    out_p = plain.run()[rp]

    spec = RollingGenerator(params, cfg, max_slots=2, steps_per_call=2,
                            spec_k=8, spec_ngram=2)
    rs = spec.submit(prompt, max_new_tokens=24)
    out_s = spec.run()[rs]
    assert out_s == out_p
    assert spec.spec_stats["tokens_per_pass"] > 1.5, spec.spec_stats


@pytest.mark.level("minimal")
def test_spec_rolling_int8_grid(model):
    """Speculation composes with the int8 serving grid: verify reads the
    quantized grid + bf16 chunk, accepted prefixes quantize at the
    merge. Same agreement bar as the plain int8-vs-bf16 test."""
    params, cfg = model
    prompts = [[3, 7, 11, 2], [5, 1], [9, 9, 9, 9, 9, 9]]
    outs = {}
    for name, kw in (("plain", {}), ("spec", {"spec_k": 4})):
        eng = RollingGenerator(params, cfg, max_slots=4, steps_per_call=2,
                               kv_dtype="int8", **kw)
        rids = [eng.submit(p, max_new_tokens=12) for p in prompts]
        res = eng.run()
        outs[name] = [res[r] for r in rids]
    # int8 quantization boundaries differ between per-round merges
    # (spec) and per-chunk merges (plain) only in that the spec path
    # reads freshly-quantized rows earlier; values written are identical
    # per token, so greedy streams agree modulo near-tie flips.
    total = sum(len(o) for o in outs["plain"])
    agree = sum(a == b for x, y in zip(outs["plain"], outs["spec"])
                for a, b in zip(x, y))
    assert agree >= int(0.7 * total), (agree, total, outs)
    first_chunk = sum(a == b for x, y in zip(outs["plain"], outs["spec"])
                      for a, b in zip(x[:4], y[:4]))
    assert first_chunk >= 11, (first_chunk, outs)


@pytest.mark.level("minimal")
def test_spec_rolling_with_adapters(model):
    """Per-request LoRA rides the verify forward: spec+adapters is
    token-identical to plain rolling+adapters."""
    import jax.numpy as jnp

    from kubetorch_tpu.models import lora as lora_mod
    from kubetorch_tpu.models.lora import LoraConfig, stack_adapters

    params, cfg = model
    lcfg = LoraConfig(rank=4, alpha=8.0)
    ads = []
    for i in range(2):
        ad = lora_mod.init(jax.random.key(i + 1), params, lcfg)
        for name in ad:
            ad[name]["b"] = (jax.random.normal(
                jax.random.key(i + 7), ad[name]["b"].shape,
                jnp.float32) * 0.2).astype(ad[name]["b"].dtype)
        ads.append(ad)
    stacked = stack_adapters(ads, lcfg)
    prompts = [[3, 7, 11], [3, 7, 11], [3, 7, 11]]
    aids = [0, 1, -1]
    outs = {}
    for name, kw in (("plain", {}), ("spec", {"spec_k": 4})):
        eng = RollingGenerator(params, cfg, max_slots=4, steps_per_call=2,
                               adapters=stacked, adapter_scale=lcfg.scale,
                               **kw)
        rids = [eng.submit(p, max_new_tokens=10, adapter_id=a)
                for p, a in zip(prompts, aids)]
        res = eng.run()
        outs[name] = [res[r] for r in rids]
    assert outs["plain"] == outs["spec"], outs
    # adapters actually steer: adapted rows differ from the base row
    assert (outs["spec"][0] != outs["spec"][2]
            or outs["spec"][1] != outs["spec"][2])


@pytest.mark.level("unit")
def test_spec_rolling_validation(model):
    params, cfg = model
    with pytest.raises(ValueError, match="spec_k"):
        RollingGenerator(params, cfg, max_slots=2, spec_k=1)
    eng = RollingGenerator(params, cfg, max_slots=2, spec_k=4,
                           steps_per_call=2)
    with pytest.raises(ValueError, match="repetition_penalty"):
        eng.submit([1, 2], max_new_tokens=4, repetition_penalty=1.3)
    # sampling is supported (exact rejection sampling per slot)
    eng.submit([1, 2], max_new_tokens=4, temperature=0.7)


@pytest.mark.level("minimal")
def test_spec_rolling_eos_and_stop(model):
    """eos/stop trimming happens host-side per chunk — identical
    behavior with speculation on (both engines see the same stream)."""
    params, cfg = model
    plain = RollingGenerator(params, cfg, max_slots=2, steps_per_call=4)
    probe = plain.submit([2, 4, 6], max_new_tokens=16)
    stream = plain.run()[probe]
    eos = stream[5]
    stop_seq = stream[2:4]

    for kw in ({}, {"spec_k": 4, "steps_per_call": 2}):
        eng = RollingGenerator(params, cfg, max_slots=2, eos_id=eos,
                               **({"steps_per_call": 4} | kw))
        r = eng.submit([2, 4, 6], max_new_tokens=16)
        out = eng.run()[r]
        assert out == stream[:6], (kw, out)
        eng2 = RollingGenerator(params, cfg, max_slots=2,
                                **({"steps_per_call": 4} | kw))
        r2 = eng2.submit([2, 4, 6], max_new_tokens=16, stop=[stop_seq])
        out2 = eng2.run()[r2]
        assert out2 == stream[:4], (kw, out2)


@pytest.mark.level("minimal")
def test_spec_rolling_with_prefix(model):
    """Speculation + shared prefix: prefix tokens seed the draft
    haystack, and the emitted stream equals the plain prefixed engine."""
    params, cfg = model
    prefix = [11, 12, 13, 14, 15]
    suffixes = [[21, 22, 23], [31]]
    outs = {}
    for name, kw in (("plain", {"steps_per_call": 4}),
                     ("spec", {"spec_k": 4, "steps_per_call": 2})):
        eng = RollingGenerator(params, cfg, max_slots=2, **kw)
        pid = eng.register_prefix(prefix)
        rids = [eng.submit(s, max_new_tokens=10, prefix_id=pid)
                for s in suffixes]
        res = eng.run()
        outs[name] = [res[r] for r in rids]
    assert outs["plain"] == outs["spec"], outs


@pytest.mark.level("minimal")
def test_serving_width_rolling_int8_parity(model):
    """Serving-shaped engine OFF-chip (VERDICT r4 weak #7): 64 slots ×
    admit_width 16 × int8 grid — wide deferred-merge/one-hot-select
    machinery regression-guarded without a TPU session. 20 staggered
    requests exercise multi-wave chunked admission, slot reuse, and the
    one-hot merge at batch widths the toy tests never reach; every
    request must match its isolated single-slot generation."""
    params, cfg = model
    rng = np.random.RandomState(7)
    prompts = [[int(t) for t in rng.randint(1, cfg.vocab_size, rng.randint(2, 12))]
               for _ in range(20)]
    budgets = [int(b) for b in rng.randint(4, 12, 20)]

    iso = {}
    ref = RollingGenerator(params, cfg, max_slots=1, steps_per_call=4,
                           kv_dtype="int8")
    for i, (p, b) in enumerate(zip(prompts, budgets)):
        rid = ref.submit(p, max_new_tokens=b)
        iso[i] = ref.run()[rid]

    eng = RollingGenerator(params, cfg, max_slots=64, steps_per_call=4,
                           admit_width=16, kv_dtype="int8")
    first = [eng.submit(p, max_new_tokens=b)
             for p, b in zip(prompts[:12], budgets[:12])]
    acc = {r: [] for r in first}
    for rid, toks, _ in eng.step():                 # one chunk in flight
        acc[rid].extend(toks)
    late = [eng.submit(p, max_new_tokens=b)         # staggered arrivals
            for p, b in zip(prompts[12:], budgets[12:])]
    for r in late:
        acc[r] = []
    for rid, toks in eng.run().items():
        acc[rid].extend(toks)

    rids = first + late
    mismatch = sum(acc[r] != iso[i] for i, r in enumerate(rids))
    # int8 near-tie flips across admission widths are possible on the toy
    # model but rare; the machinery bar is: every stream full-length and
    # almost all streams identical to isolated generation
    assert all(len(acc[r]) == budgets[i] for i, r in enumerate(rids)), acc
    assert mismatch <= 2, (
        mismatch, [(acc[r], iso[i]) for i, r in enumerate(rids)
                   if acc[r] != iso[i]])


@pytest.mark.level("minimal")
def test_spec_rolling_sampled_matches_plain_distribution(model):
    """temperature>0 on a speculative engine: exact per-slot rejection
    sampling — the emitted stream must be distributed as non-speculative
    sampling. Monte-Carlo over the first two tokens (top_k=4 keeps the
    support small), identical prompts as independent requests."""
    import collections

    params, cfg = model
    B = 768
    prompt = [3, 7, 11, 2, 9]

    def hist(eng):
        rids = [eng.submit(list(prompt), max_new_tokens=2,
                           temperature=1.0) for _ in range(B)]
        res = eng.run()
        return collections.Counter(tuple(res[r]) for r in rids)

    plain = RollingGenerator(params, cfg, max_slots=128, top_k=4,
                             steps_per_call=2, seed=11)
    h_plain = hist(plain)
    spec = RollingGenerator(params, cfg, max_slots=128, top_k=4,
                            steps_per_call=1, spec_k=4, seed=22)
    h_spec = hist(spec)
    keys = set(h_plain) | set(h_spec)
    tv = 0.5 * sum(abs(h_plain.get(t, 0) / B - h_spec.get(t, 0) / B)
                   for t in keys)
    assert tv < 0.12, (tv, h_plain.most_common(5), h_spec.most_common(5))


@pytest.mark.level("minimal")
def test_spec_rolling_sampled_accepts_drafts(model):
    """Sampling must still ACCEPT drafts on loopy low-temperature
    traffic (zero-acceptance rejection sampling is just plain sampling —
    the distribution test alone can't see that regression)."""
    params, cfg = model
    gen = Generator(params, cfg)
    warm = gen.generate([[5, 9, 13]], max_new_tokens=32,
                        temperature=0.0)[0]
    loopy = [5, 9, 13] + warm[:24]
    eng = RollingGenerator(params, cfg, max_slots=4, spec_k=8,
                           spec_ngram=2, steps_per_call=2, top_k=4,
                           seed=3)
    rids = [eng.submit(list(loopy), max_new_tokens=16, temperature=0.2)
            for _ in range(4)]
    res = eng.run()
    assert all(len(res[r]) == 16 for r in rids)
    assert eng.spec_stats["tokens_per_pass"] > 1.0, eng.spec_stats


@pytest.mark.level("minimal")
def test_spec_rolling_service_token_streaming(model):
    """generate_iter yields tokens INCREMENTALLY from a speculative
    engine (per decode chunk, one int at a time) — verified by
    observing the first token while the request is still mid-flight,
    under a deadline so a dead driver thread fails instead of hanging."""
    import queue as _queue
    import threading

    from kubetorch_tpu.models.rolling import RollingService

    params, cfg = model
    svc = RollingService(RollingGenerator(params, cfg, max_slots=2,
                                          spec_k=4, steps_per_call=1))
    plain = RollingGenerator(params, cfg, max_slots=2, steps_per_call=4)
    rid = plain.submit([1, 2, 3], max_new_tokens=10)
    want = plain.run()[rid]

    seen = _queue.Queue()
    got = []

    def consume():
        for i, tok in enumerate(svc.generate_iter([1, 2, 3],
                                                  max_new_tokens=10)):
            got.append(tok)
            if i == 0:
                # first token observed while the request is still
                # decoding — incremental delivery, not a drained batch
                seen.put(svc.engine.pending)
        seen.put("done")

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    pending_at_first = seen.get(timeout=60)
    assert pending_at_first > 0, "first token arrived only after drain"
    assert seen.get(timeout=60) == "done"
    t.join(10)
    assert not t.is_alive()
    assert got == want, (got, want)


@pytest.mark.level("minimal")
def test_spec_warmup_compiles_sampling_executable(model):
    """warmup(sampling=True) pre-flips the sticky sampling upgrade so
    the first temperature>0 request doesn't compile mid-traffic; the
    engine still serves greedy traffic identically afterwards."""
    params, cfg = model
    eng = RollingGenerator(params, cfg, max_slots=2, spec_k=4,
                           steps_per_call=2)
    eng.warmup(prompt_buckets=(16,), sampling=True)
    assert eng._spec_sampling
    plain = RollingGenerator(params, cfg, max_slots=2, steps_per_call=4)
    rid_p = plain.submit([1, 2, 3], max_new_tokens=8)
    want = plain.run()[rid_p]
    rid = eng.submit([1, 2, 3], max_new_tokens=8)       # greedy request
    assert eng.run()[rid] == want
    rid_s = eng.submit([1, 2, 3], max_new_tokens=8, temperature=0.9)
    assert len(eng.run()[rid_s]) == 8


@pytest.mark.level("minimal")
def test_rolling_decoder_remote_facing_driver(model):
    """RollingDecoder: the JSON-able submit/step wrapper driven through
    the pipelined call channel. Events must be plain types (survive the
    json wire), match the engine's own output, and step() must report
    the measured device time the latency decomposition checks against."""
    import json

    from kubetorch_tpu.models.rolling import RollingDecoder

    params, cfg = model
    eng = RollingGenerator(params, cfg, max_slots=4)
    dec = RollingDecoder(eng)
    rid = dec.submit([1, 2, 3, 4, 5], max_new_tokens=10)
    got = []
    while True:
        out = dec.step()
        json.dumps(out)  # the whole step result must be wire-safe
        assert out["device_ms"] > 0
        for erid, toks, done in out["events"]:
            if erid == rid:
                got.extend(toks)
        if not out["pending"]:
            break
    gen = Generator(params, cfg)
    expect = gen.generate([[1, 2, 3, 4, 5]], max_new_tokens=10,
                          temperature=0.0, seed=0)[0]
    assert got == expect
    assert dec.stats()["free_slots"] == 4


# ---------------------------------------------------------------------
# ISSUE 10: row-granular admission (splice correctness), eviction, and
# chunked grid-resident prefill — the model-level half of the serving
# engine's scheduler.


@pytest.mark.level("minimal")
def test_admit_into_live_batch_splices_identically(model):
    """_admit_group/_finish_admit splice correctness: a row admitted
    into a LIVE batch (neighbor rows mid-decode at depth) decodes
    token-identically to a fresh-batch run of the same prompt."""
    params, cfg = model
    p_bg, p_new = [1, 2, 3, 4], [42, 17, 9]
    gen = Generator(params, cfg)
    iso_new = gen.generate([p_new], max_new_tokens=8, temperature=0.0)[0]

    eng = RollingGenerator(params, cfg, max_slots=3)
    eng.submit(p_bg, max_new_tokens=24)
    eng.step()
    eng.step()                       # background row is deep in decode
    rid = eng.submit(p_new, max_new_tokens=8)
    got = []
    while eng.pending:
        for r, toks, done in eng.step():
            if r == rid:
                got.extend(toks)
    assert got == iso_new, (got, iso_new)


@pytest.mark.level("minimal")
def test_evicted_row_cache_plane_is_reusable(model):
    """evict() frees the row immediately and a new request admitted
    into the SAME slot decodes identically to a fresh-batch run — the
    stale K/V beyond the new depth is never attended."""
    params, cfg = model
    gen = Generator(params, cfg)
    iso = gen.generate([[9, 8, 7]], max_new_tokens=6, temperature=0.0)[0]

    eng = RollingGenerator(params, cfg, max_slots=1)   # one row only
    ra = eng.submit([1, 2, 3, 4, 5, 6], max_new_tokens=40)
    eng.step()
    eng.step()                                # row holds deep stale K/V
    assert eng.evict(ra)
    assert not eng.evict(ra)                  # second evict: gone
    assert eng.free_rows == 1
    rc = eng.submit([9, 8, 7], max_new_tokens=6)
    out = []
    while eng.pending:
        for r, toks, done in eng.step():
            assert r == rc, "evicted rid must never emit again"
            out.extend(toks)
    assert out == iso, (out, iso)


@pytest.mark.level("minimal")
def test_evict_queued_and_prefilling(model):
    params, cfg = model
    eng = RollingGenerator(params, cfg, max_slots=1, prefill_chunk=8)
    ra = eng.submit([1, 2], max_new_tokens=30)
    rb = eng.submit([3, 4], max_new_tokens=4)          # queued behind a
    assert eng.evict(rb)                               # queued evict
    assert eng.queued == 1                             # only ra remains
    eng.step()
    # long prompt enters chunked prefill once the row frees
    eng.evict(ra)
    rc = eng.submit(list(range(1, 25)), max_new_tokens=4)
    eng.admit()
    assert eng.prefilling_rows == 1
    assert eng.evict(rc)                               # mid-prefill evict
    assert eng.prefilling_rows == 0 and eng.free_rows == 1
    assert eng.pending == 0


@pytest.mark.level("minimal")
def test_chunked_prefill_token_identity_and_no_stall(model):
    """A long prompt prefilled in chunks interleaved with decode steps
    yields byte-identical tokens to its isolated run, and the live
    neighbor row emits on EVERY step of the prefill window (no decode
    stall)."""
    params, cfg = model
    gen = Generator(params, cfg)
    long_p = list(range(1, 25))                        # 24 toks, chunk 8
    short_p = [5, 6, 7]
    iso_long = gen.generate([long_p], max_new_tokens=10,
                            temperature=0.0)[0]
    iso_short = gen.generate([short_p], max_new_tokens=40,
                             temperature=0.0)[0]

    eng = RollingGenerator(params, cfg, max_slots=4, prefill_chunk=8)
    rs = eng.submit(short_p, max_new_tokens=40)
    seen = {rs: []}
    for _, toks, _ in eng.step():                      # short is live
        seen[rs].extend(toks)
    rl = eng.submit(long_p, max_new_tokens=10)
    seen[rl] = []
    prefill_window_emits = []
    while eng.pending:
        prefilling = eng.prefilling_rows > 0 or eng.queued > 0
        events = eng.step()
        if prefilling:
            prefill_window_emits.append(
                any(r == rs and toks for r, toks, _ in events))
        for r, toks, done in events:
            seen[r].extend(toks)
    assert seen[rl] == iso_long, (seen[rl], iso_long)
    assert seen[rs] == iso_short, (seen[rs], iso_short)
    # every step of the prefill window also emitted live tokens
    assert prefill_window_emits and all(prefill_window_emits), \
        prefill_window_emits


@pytest.mark.level("minimal")
def test_chunked_prefill_matches_oneshot_admission(model):
    """The chunked grid-resident prefill and the one-shot private-cache
    admission are the same function of the prompt: identical greedy
    tokens from either path."""
    params, cfg = model
    prompt = list(range(7, 47))                        # 40 tokens
    eng_a = RollingGenerator(params, cfg, max_slots=2)
    ra = eng_a.submit(prompt, max_new_tokens=12)
    out_a = eng_a.run()[ra]
    eng_b = RollingGenerator(params, cfg, max_slots=2, prefill_chunk=16)
    rb = eng_b.submit(prompt, max_new_tokens=12)
    out_b = eng_b.run()[rb]
    assert out_a == out_b, (out_a, out_b)


@pytest.mark.level("minimal")
def test_chunked_prefill_composes_with_spec(model):
    """ISSUE 14 tentpole: the prefill_chunk × spec_k ctor
    incompatibility is LIFTED — a long prompt prefills into the grid
    chunk by chunk and the draft haystack seeds at activation, so the
    spec stream stays token-identical to the plain engine's. Bad chunk
    sizes still raise."""
    params, cfg = model
    prompt = [(i * 7) % 50 + 2 for i in range(40)]   # > chunk of 16
    plain = RollingGenerator(params, cfg, max_slots=2)
    rp = plain.submit(prompt, max_new_tokens=12)
    out_p = plain.run()[rp]
    spec = RollingGenerator(params, cfg, max_slots=2, prefill_chunk=16,
                            spec_k=4, steps_per_call=2)
    rs = spec.submit(prompt, max_new_tokens=12)
    out_s = spec.run()[rs]
    assert out_p == out_s, (out_p, out_s)
    assert spec.spec_stats["rounds"] > 0
    with pytest.raises(ValueError):
        RollingGenerator(params, cfg, max_slots=2, prefill_chunk=0)
