"""LoRA adapters: zero-effect init, exact merge math, frozen-base
fine-tuning through the Trainer, serving composition, size accounting."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubetorch_tpu.models import LlamaConfig, llama
from kubetorch_tpu.models import lora as lora_mod
from kubetorch_tpu.models.lora import LoraConfig
from kubetorch_tpu.parallel import MeshSpec

pytestmark = pytest.mark.level("unit")


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init(jax.random.key(0), cfg)


def test_init_is_zero_effect(cfg, params):
    lcfg = LoraConfig(rank=4)
    adapters = lora_mod.init(jax.random.key(1), params, lcfg)
    merged = lora_mod.merge(params, adapters, lcfg)
    toks = jnp.array([[3, 1, 4, 1, 5]])
    np.testing.assert_allclose(
        np.asarray(llama.forward(params, toks, cfg)),
        np.asarray(llama.forward(merged, toks, cfg)), rtol=0, atol=0)


def test_merge_math_is_exact(cfg, params):
    lcfg = LoraConfig(rank=2, alpha=8.0, targets=("wq",))
    adapters = lora_mod.init(jax.random.key(2), params, lcfg)
    adapters["wq"]["b"] = jax.random.normal(
        jax.random.key(3), adapters["wq"]["b"].shape,
        adapters["wq"]["b"].dtype)
    merged = lora_mod.merge(params, adapters, lcfg)
    l0 = 1
    expect = (params["layers"]["wq"][l0].astype(jnp.float32)
              + (8.0 / 2)
              * adapters["wq"]["a"][l0].astype(jnp.float32)
              @ adapters["wq"]["b"][l0].astype(jnp.float32))
    np.testing.assert_allclose(
        np.asarray(merged["layers"]["wq"][l0]),
        np.asarray(expect.astype(params["layers"]["wq"].dtype)),
        rtol=1e-6, atol=1e-6)
    # untargeted weights are the same objects
    assert merged["layers"]["w_up"] is params["layers"]["w_up"]


def test_unknown_target_raises(cfg, params):
    with pytest.raises(ValueError, match="no lora targets"):
        lora_mod.init(jax.random.key(0), params,
                      LoraConfig(targets=("nope",)))


def test_lora_trainer_learns_with_frozen_base(cfg, params):
    from kubetorch_tpu.training.trainer import Trainer

    mesh = MeshSpec(dp=-1).build()
    lcfg = LoraConfig(rank=4, alpha=8.0)
    trainer = Trainer.lora(
        cfg, mesh, params, lcfg,
        optimizer=optax.adamw(1e-2))
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (4, 33))
    batch = {"inputs": jnp.asarray(toks[:, :-1], jnp.int32),
             "targets": jnp.asarray(toks[:, 1:], jnp.int32)}
    losses = [float(trainer.step(batch)["loss"]) for _ in range(8)]
    assert losses[-1] < losses[0] - 0.05, losses
    # the trained tree IS the adapter tree (adapter-sized optimizer state)
    assert set(trainer.state["params"]) <= set(LoraConfig().targets)
    # base params were never touched
    np.testing.assert_array_equal(
        np.asarray(params["layers"]["wq"]),
        np.asarray(llama.init(jax.random.key(0), cfg)["layers"]["wq"]))
    # merged model actually changed
    merged = lora_mod.merge(params, trainer.state["params"], lcfg)
    assert not np.allclose(np.asarray(merged["layers"]["wq"]),
                           np.asarray(params["layers"]["wq"]))


def test_merged_adapters_serve_and_quantize(cfg, params):
    from kubetorch_tpu.models.generate import Generator
    from kubetorch_tpu.models.quant import quantize_params

    lcfg = LoraConfig(rank=4)
    adapters = lora_mod.init(jax.random.key(5), params, lcfg)
    adapters = jax.tree.map(
        lambda x: x + 0.01 if x.ndim == 3 else x, adapters)
    merged = lora_mod.merge(params, adapters, lcfg)
    out = Generator(merged, cfg).generate(
        [[3, 1, 4]], max_new_tokens=4, temperature=0.0)
    assert len(out[0]) == 4
    qmerged = jax.jit(quantize_params)(merged)
    out_q = Generator(qmerged, cfg).generate(
        [[3, 1, 4]], max_new_tokens=4, temperature=0.0)
    assert len(out_q[0]) == 4


def test_adapter_bytes_are_tiny(cfg, params):
    lcfg = LoraConfig(rank=8)
    adapters = lora_mod.init(jax.random.key(6), params, lcfg)
    base_bytes = sum(x.size * x.dtype.itemsize
                     for x in jax.tree.leaves(params))
    assert lora_mod.nbytes(adapters) < 0.2 * base_bytes
    assert lora_mod.num_params(adapters) > 0
