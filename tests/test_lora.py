"""LoRA adapters: zero-effect init, exact merge math, frozen-base
fine-tuning through the Trainer, serving composition, size accounting."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubetorch_tpu.models import LlamaConfig, llama
from kubetorch_tpu.models import lora as lora_mod
from kubetorch_tpu.models.lora import LoraConfig
from kubetorch_tpu.parallel import MeshSpec

pytestmark = pytest.mark.level("unit")


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init(jax.random.key(0), cfg)


def test_init_is_zero_effect(cfg, params):
    lcfg = LoraConfig(rank=4)
    adapters = lora_mod.init(jax.random.key(1), params, lcfg)
    merged = lora_mod.merge(params, adapters, lcfg)
    toks = jnp.array([[3, 1, 4, 1, 5]])
    np.testing.assert_allclose(
        np.asarray(llama.forward(params, toks, cfg)),
        np.asarray(llama.forward(merged, toks, cfg)), rtol=0, atol=0)


def test_merge_math_is_exact(cfg, params):
    lcfg = LoraConfig(rank=2, alpha=8.0, targets=("wq",))
    adapters = lora_mod.init(jax.random.key(2), params, lcfg)
    adapters["wq"]["b"] = jax.random.normal(
        jax.random.key(3), adapters["wq"]["b"].shape,
        adapters["wq"]["b"].dtype)
    merged = lora_mod.merge(params, adapters, lcfg)
    l0 = 1
    expect = (params["layers"]["wq"][l0].astype(jnp.float32)
              + (8.0 / 2)
              * adapters["wq"]["a"][l0].astype(jnp.float32)
              @ adapters["wq"]["b"][l0].astype(jnp.float32))
    np.testing.assert_allclose(
        np.asarray(merged["layers"]["wq"][l0]),
        np.asarray(expect.astype(params["layers"]["wq"].dtype)),
        rtol=1e-6, atol=1e-6)
    # untargeted weights are the same objects
    assert merged["layers"]["w_up"] is params["layers"]["w_up"]


def test_unknown_target_raises(cfg, params):
    with pytest.raises(ValueError, match="no lora targets"):
        lora_mod.init(jax.random.key(0), params,
                      LoraConfig(targets=("nope",)))


def test_lora_trainer_learns_with_frozen_base(cfg, params):
    from kubetorch_tpu.training.trainer import Trainer

    mesh = MeshSpec(dp=-1).build()
    lcfg = LoraConfig(rank=4, alpha=8.0)
    trainer = Trainer.lora(
        cfg, mesh, params, lcfg,
        optimizer=optax.adamw(1e-2))
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (4, 33))
    batch = {"inputs": jnp.asarray(toks[:, :-1], jnp.int32),
             "targets": jnp.asarray(toks[:, 1:], jnp.int32)}
    losses = [float(trainer.step(batch)["loss"]) for _ in range(8)]
    assert losses[-1] < losses[0] - 0.05, losses
    # the trained tree IS the adapter tree (adapter-sized optimizer state)
    assert set(trainer.state["params"]) <= set(LoraConfig().targets)
    # base params were never touched
    np.testing.assert_array_equal(
        np.asarray(params["layers"]["wq"]),
        np.asarray(llama.init(jax.random.key(0), cfg)["layers"]["wq"]))
    # merged model actually changed
    merged = lora_mod.merge(params, trainer.state["params"], lcfg)
    assert not np.allclose(np.asarray(merged["layers"]["wq"]),
                           np.asarray(params["layers"]["wq"]))


def test_merged_adapters_serve_and_quantize(cfg, params):
    from kubetorch_tpu.models.generate import Generator
    from kubetorch_tpu.models.quant import quantize_params

    lcfg = LoraConfig(rank=4)
    adapters = lora_mod.init(jax.random.key(5), params, lcfg)
    adapters = jax.tree.map(
        lambda x: x + 0.01 if x.ndim == 3 else x, adapters)
    merged = lora_mod.merge(params, adapters, lcfg)
    out = Generator(merged, cfg).generate(
        [[3, 1, 4]], max_new_tokens=4, temperature=0.0)
    assert len(out[0]) == 4
    qmerged = jax.jit(quantize_params)(merged)
    out_q = Generator(qmerged, cfg).generate(
        [[3, 1, 4]], max_new_tokens=4, temperature=0.0)
    assert len(out_q[0]) == 4


def test_adapter_bytes_are_tiny(cfg, params):
    lcfg = LoraConfig(rank=8)
    adapters = lora_mod.init(jax.random.key(6), params, lcfg)
    base_bytes = sum(x.size * x.dtype.itemsize
                     for x in jax.tree.leaves(params))
    assert lora_mod.nbytes(adapters) < 0.2 * base_bytes
    assert lora_mod.num_params(adapters) > 0


# ---------------------------------------------------------- multi-adapter
def _noisy_adapters(key, params, lcfg, scale=0.05):
    ad = lora_mod.init(key, params, lcfg)
    ks = jax.random.split(key, len(ad))
    for k, name in zip(ks, sorted(ad)):
        ad[name]["b"] = (jax.random.normal(k, ad[name]["b"].shape,
                                           jnp.float32) * scale
                         ).astype(ad[name]["b"].dtype)
    return ad


def test_multi_adapter_prefill_logits_match_merged(cfg, params):
    from kubetorch_tpu.models.lora import stack_adapters

    lcfg = LoraConfig(rank=4, alpha=8.0)
    ads = [_noisy_adapters(jax.random.key(i + 10), params, lcfg)
           for i in range(2)]
    stacked = stack_adapters(ads, lcfg)
    B, P, M = 3, 6, 10
    toks = jax.random.randint(jax.random.key(1), (B, P), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(P)[None], (B, P))
    mask = jnp.broadcast_to(
        jnp.arange(M)[None, None, :] <= jnp.arange(P)[None, :, None],
        (B, P, M))
    slots = jnp.asarray([0, 1, -1], jnp.int32)
    cache = llama.init_cache(cfg, B, M)
    got, _ = llama.forward_cached(
        params, toks, positions, cache, 0, mask, cfg,
        lora={"adapters": stacked, "slots": slots, "scale": lcfg.scale})
    # row 0 ≡ merged adapter 0, row 1 ≡ merged adapter 1, row 2 ≡ base
    for row, ref_params in ((0, lora_mod.merge(params, ads[0], lcfg)),
                            (1, lora_mod.merge(params, ads[1], lcfg)),
                            (2, params)):
        cache2 = llama.init_cache(cfg, 1, M)
        ref, _ = llama.forward_cached(
            ref_params, toks[row:row + 1], positions[:1], cache2, 0,
            mask[row:row + 1], cfg)
        np.testing.assert_allclose(np.asarray(got[row]),
                                   np.asarray(ref[0]),
                                   rtol=2e-4, atol=2e-4)


def test_multi_adapter_generate_per_request(cfg, params):
    from kubetorch_tpu.models.generate import Generator
    from kubetorch_tpu.models.lora import stack_adapters

    lcfg = LoraConfig(rank=4, alpha=8.0)
    ads = [_noisy_adapters(jax.random.key(i + 20), params, lcfg, 0.2)
           for i in range(2)]
    stacked = stack_adapters(ads, lcfg)
    gen = Generator(params, cfg, adapters=stacked,
                    adapter_scale=lcfg.scale)
    prompts = [[3, 7, 11], [3, 7, 11], [3, 7, 11]]
    out = gen.generate(prompts, max_new_tokens=6, temperature=0.0,
                       adapter_ids=[0, 1, -1])
    # the base row must be token-identical to a no-adapter Generator
    # (the −1 index masks the delta to exactly zero)
    base = Generator(params, cfg).generate([prompts[2]], max_new_tokens=6,
                                           temperature=0.0)
    assert out[2] == base[0]
    # different adapters actually steer generation apart
    assert out[0] != out[2] or out[1] != out[2]
    # merged single-adapter generation agrees with the batched select
    m0 = Generator(lora_mod.merge(params, ads[0], lcfg), cfg).generate(
        [prompts[0]], max_new_tokens=6, temperature=0.0)
    assert out[0] == m0[0]


def test_multi_adapter_fused_quantized_serving(cfg, params):
    from kubetorch_tpu.models.generate import Generator
    from kubetorch_tpu.models.lora import stack_adapters
    from kubetorch_tpu.models.quant import (
        fuse_decode_layers,
        quantize_params,
    )

    lcfg = LoraConfig(rank=4, alpha=8.0)
    ads = [_noisy_adapters(jax.random.key(i + 30), params, lcfg, 0.2)
           for i in range(2)]
    qparams = jax.jit(quantize_params)(params)
    qparams = {**qparams, "layers": fuse_decode_layers(qparams["layers"])}
    stacked = stack_adapters(ads, lcfg,
                             layer_names=set(qparams["layers"]))
    assert "wqkv" in stacked and "wgu" in stacked
    gen = Generator(qparams, cfg, kv_dtype="int8", adapters=stacked,
                    adapter_scale=lcfg.scale)
    prompts = [[2, 4, 6], [2, 4, 6]]
    out = gen.generate(prompts, max_new_tokens=5, temperature=0.0,
                       adapter_ids=[0, -1])
    assert all(len(o) == 5 for o in out)
    base = Generator(qparams, cfg, kv_dtype="int8").generate(
        [prompts[1]], max_new_tokens=5, temperature=0.0)
    assert out[1] == base[0]


def test_adapter_id_validation(cfg, params):
    from kubetorch_tpu.models.generate import Generator
    from kubetorch_tpu.models.lora import stack_adapters

    lcfg = LoraConfig(rank=2)
    stacked = stack_adapters(
        [lora_mod.init(jax.random.key(0), params, lcfg)], lcfg)
    with pytest.raises(ValueError, match="adapter_scale"):
        Generator(params, cfg, adapters=stacked)
    gen = Generator(params, cfg, adapters=stacked,
                    adapter_scale=lcfg.scale)
    with pytest.raises(ValueError, match="out of range"):
        gen.generate([[1, 2]], max_new_tokens=2, adapter_ids=[3])
    with pytest.raises(ValueError, match="no .*adapters|adapters"):
        Generator(params, cfg).generate([[1, 2]], max_new_tokens=2,
                                        adapter_ids=[0])


def test_multi_adapter_rolling_matches_static(cfg, params):
    from kubetorch_tpu.models.generate import Generator
    from kubetorch_tpu.models.lora import stack_adapters
    from kubetorch_tpu.models.rolling import RollingGenerator

    lcfg = LoraConfig(rank=4, alpha=8.0)
    ads = [_noisy_adapters(jax.random.key(i + 40), params, lcfg, 0.2)
           for i in range(2)]
    stacked = stack_adapters(ads, lcfg)
    eng = RollingGenerator(params, cfg, max_slots=4, steps_per_call=4,
                           adapters=stacked, adapter_scale=lcfg.scale)
    prompt = [3, 7, 11]
    r0 = eng.submit(prompt, max_new_tokens=8, adapter_id=0)
    r1 = eng.submit(prompt, max_new_tokens=8, adapter_id=1)
    rb = eng.submit(prompt, max_new_tokens=8)            # base
    out = eng.run()

    gen = Generator(params, cfg, adapters=stacked, adapter_scale=lcfg.scale)
    ref = gen.generate([prompt] * 3, max_new_tokens=8, temperature=0.0,
                       adapter_ids=[0, 1, -1])
    assert out[r0] == ref[0]
    assert out[r1] == ref[1]
    assert out[rb] == ref[2]
    # adapters released with the slot: a follow-up base request on a
    # reused slot must not inherit the old adapter
    rb2 = eng.submit(prompt, max_new_tokens=8)
    out2 = eng.run()
    assert out2[rb2] == ref[2]


def test_rolling_adapter_validation(cfg, params):
    from kubetorch_tpu.models.lora import stack_adapters
    from kubetorch_tpu.models.rolling import RollingGenerator

    lcfg = LoraConfig(rank=2)
    stacked = stack_adapters(
        [lora_mod.init(jax.random.key(0), params, lcfg)], lcfg)
    with pytest.raises(ValueError, match="adapter_scale"):
        RollingGenerator(params, cfg, adapters=stacked)
    eng = RollingGenerator(params, cfg, max_slots=2, adapters=stacked,
                           adapter_scale=lcfg.scale)
    with pytest.raises(ValueError, match="out of range"):
        eng.submit([1, 2], adapter_id=5)
    # prefix KV is weight-dependent: a base-model prefix cannot serve an
    # adapted request (register a per-adapter prefix instead)
    pid = eng.register_prefix([1, 2, 3, 4])
    with pytest.raises(ValueError, match="weight-dependent"):
        eng.submit([5], prefix_id=pid, adapter_id=0)
    pid0 = eng.register_prefix([1, 2, 3, 4], adapter_id=0)
    with pytest.raises(ValueError, match="weight-dependent"):
        eng.submit([5], prefix_id=pid0, adapter_id=-1)
    with pytest.raises(ValueError, match="out of range"):
        eng.register_prefix([1, 2], adapter_id=7)
    plain = RollingGenerator(params, cfg, max_slots=2)
    with pytest.raises(ValueError, match="no .*adapters|adapters"):
        plain.submit([1, 2], adapter_id=0)


def test_stack_partial_fused_coverage_raises(cfg, params):
    from kubetorch_tpu.models.lora import stack_adapters

    lcfg = LoraConfig(rank=2, targets=("wq", "wv", "wo"))
    ads = [lora_mod.init(jax.random.key(0), params, lcfg)]
    with pytest.raises(ValueError, match="cover all of"):
        stack_adapters(ads, lcfg, layer_names={"wqkv", "wo", "w_down"})
    # unfused layout: partial targets are fine
    out = stack_adapters(ads, lcfg)
    assert set(out) == {"wq", "wv", "wo"}


def test_fused_tree_unfused_adapters_rejected(cfg, params):
    """Adapters stacked WITHOUT layer_names must not silently lose their
    qkv/gate-up deltas on a fused serving tree (ADVICE r4 medium)."""
    from kubetorch_tpu.models.generate import Generator
    from kubetorch_tpu.models.lora import stack_adapters
    from kubetorch_tpu.models.quant import (
        fuse_decode_layers,
        quantize_params,
    )
    from kubetorch_tpu.models.rolling import RollingGenerator

    lcfg = LoraConfig(rank=2)
    stacked = stack_adapters(
        [lora_mod.init(jax.random.key(0), params, lcfg)], lcfg)
    qparams = jax.jit(quantize_params)(params)
    qparams = {**qparams, "layers": fuse_decode_layers(qparams["layers"])}
    with pytest.raises(ValueError, match="stack_adapters"):
        Generator(qparams, cfg, kv_dtype="int8", adapters=stacked,
                  adapter_scale=lcfg.scale)
    with pytest.raises(ValueError, match="stack_adapters"):
        RollingGenerator(qparams, cfg, kv_dtype="int8", adapters=stacked,
                         adapter_scale=lcfg.scale)
    # correctly re-stacked adapters pass the same check
    ok = stack_adapters([lora_mod.init(jax.random.key(0), params, lcfg)],
                        lcfg, layer_names=set(qparams["layers"]))
    Generator(qparams, cfg, kv_dtype="int8", adapters=ok,
              adapter_scale=lcfg.scale)


def test_rolling_negative_adapter_id_rejected(cfg, params):
    from kubetorch_tpu.models.lora import stack_adapters
    from kubetorch_tpu.models.rolling import RollingGenerator

    lcfg = LoraConfig(rank=2)
    stacked = stack_adapters(
        [lora_mod.init(jax.random.key(0), params, lcfg)], lcfg)
    eng = RollingGenerator(params, cfg, max_slots=2, adapters=stacked,
                           adapter_scale=lcfg.scale)
    with pytest.raises(ValueError, match="out of range"):
        eng.submit([1, 2], adapter_id=-5)
    # -1 = base model stays valid
    eng.submit([1, 2], max_new_tokens=2, adapter_id=-1)


def test_fused_stack_block_diagonal_matches_unfused_math(cfg, params):
    """PR 16 satellite: the fused serving layout (A concat on the rank
    axis, B block-diagonal over the concatenated output) is
    ALGEBRAICALLY the per-target deltas laid side by side — per slot,
    per layer, to float32 exactness."""
    from kubetorch_tpu.models.lora import stack_adapters
    from kubetorch_tpu.models.quant import FUSE_GROUPS

    lcfg = LoraConfig(rank=3, alpha=6.0)
    ads = [_noisy_adapters(jax.random.key(i + 50), params, lcfg, 0.1)
           for i in range(3)]
    unfused = stack_adapters(ads, lcfg)
    fused = stack_adapters(
        ads, lcfg, layer_names={"wqkv", "wgu", "wo", "w_down"})
    assert set(fused) == {"wqkv", "wgu", "wo", "w_down"}
    for fused_name, members in FUSE_GROUPS:
        fa = fused[fused_name]["a"].astype(jnp.float32)
        fb = fused[fused_name]["b"].astype(jnp.float32)
        # [L, n, K, sum(N)] delta through the fused factors
        got = jnp.einsum("lnkr,lnrm->lnkm", fa, fb)
        want = jnp.concatenate(
            [jnp.einsum("lnkr,lnrm->lnkm",
                        unfused[m]["a"].astype(jnp.float32),
                        unfused[m]["b"].astype(jnp.float32))
             for m in members], axis=-1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)
    # untouched targets pass through identical
    np.testing.assert_array_equal(np.asarray(fused["wo"]["a"]),
                                  np.asarray(unfused["wo"]["a"]))


def test_validate_adapter_targets_messages_pinned(cfg, params):
    """The fail-fast messages engines rely on are API: the fused-tree
    hint must name stack_adapters(..., layer_names=) and the plain miss
    must list what the layer dict has."""
    from kubetorch_tpu.models.lora import validate_adapter_targets

    layers_fused = {"wqkv": 1, "wgu": 1, "wo": 1, "w_down": 1}
    with pytest.raises(ValueError) as err:
        validate_adapter_targets(
            {"wq": {}, "wk": {}, "wv": {}, "wo": {}}, layers_fused)
    msg = str(err.value)
    assert "adapter targets ['wk', 'wq', 'wv'] are absent" in msg
    assert "FUSED weights ['wqkv']" in msg
    assert "stack_adapters(..., layer_names=params['layers'])" in msg
    with pytest.raises(ValueError) as err2:
        validate_adapter_targets({"nope": {}}, {"wq": 1, "wo": 1})
    assert ("adapter targets ['nope'] not found in the serving layer "
            "dict (have ['wo', 'wq'])") in str(err2.value)
    # full coverage: silent success
    validate_adapter_targets(
        {"wqkv": {}, "wgu": {}, "wo": {}}, layers_fused)


def test_stack_partial_fuse_message_pinned(cfg, params):
    from kubetorch_tpu.models.lora import stack_adapters

    lcfg = LoraConfig(rank=2, targets=("wq", "wv", "wo"))
    ads = [lora_mod.init(jax.random.key(0), params, lcfg)]
    with pytest.raises(ValueError) as err:
        stack_adapters(ads, lcfg, layer_names={"wqkv", "wo"})
    msg = str(err.value)
    assert "cover all of ('wq', 'wk', 'wv') or none" in msg
    assert "have ('wq', 'wv')" in msg
    assert "serve unfused" in msg


def test_pad_adapter_slots_fixed_axis(cfg, params):
    """PR 16: the pool's fixed-axis contract — padded tail slots are
    exact zero deltas (serve the base model), and over-padding an
    already-wider tree refuses with the KT_LORA_SLOTS hint."""
    from kubetorch_tpu.models.generate import Generator
    from kubetorch_tpu.models.lora import pad_adapter_slots, stack_adapters

    lcfg = LoraConfig(rank=2, alpha=4.0)
    ads = [_noisy_adapters(jax.random.key(60), params, lcfg, 0.2)]
    padded = pad_adapter_slots(stack_adapters(ads, lcfg), 4)
    assert all(ab["a"].shape[1] == 4 and ab["b"].shape[1] == 4
               for ab in padded.values())
    gen = Generator(params, cfg, adapters=padded,
                    adapter_scale=lcfg.scale)
    prompt = [3, 7, 11]
    out = gen.generate([prompt] * 3, max_new_tokens=6, temperature=0.0,
                       adapter_ids=[0, 2, -1])
    base = Generator(params, cfg).generate([prompt], max_new_tokens=6,
                                           temperature=0.0)
    assert out[1] == base[0]          # zero-padded slot == base model
    assert out[2] == base[0]
    assert out[0] != base[0]          # the loaded slot still steers
    with pytest.raises(ValueError, match="raise KT_LORA_SLOTS"):
        pad_adapter_slots(padded, 2)
