"""Fused chunked cross-entropy vs. the naive logits path.

The fused op (ops/xent.py) must match forward()+cross_entropy_loss to float
tolerance — loss, aux metrics, AND gradients (it's the Trainer's default LM
objective)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubetorch_tpu.models import LlamaConfig, llama
from kubetorch_tpu.ops.xent import fused_cross_entropy, _pad_to_multiple
from kubetorch_tpu.training import cross_entropy_loss

pytestmark = pytest.mark.level("unit")


def _setup(vocab=97, batch=2, seq=12, embed=16):
    k = jax.random.key(0)
    hidden = jax.random.normal(k, (batch, seq, embed), jnp.float32)
    head = jax.random.normal(jax.random.key(1), (embed, vocab), jnp.float32)
    targets = jax.random.randint(jax.random.key(2), (batch, seq), 0, vocab)
    return hidden, head, targets


def test_pad_to_multiple():
    for n in (1, 7, 24, 4096, 6144):
        for chunk in (1, 5, 1024):
            p = _pad_to_multiple(n, chunk)
            assert p >= n and p % chunk == 0 and p - n < chunk


def test_prime_token_count_matches_naive():
    # B*S with no friendly divisor must still chunk (padding, not chunk=1)
    hidden, head, targets = _setup(batch=1, seq=13)
    naive, _ = cross_entropy_loss(
        jnp.einsum("bse,ev->bsv", hidden, head), targets)
    fused, faux = fused_cross_entropy(hidden, head, targets, chunk_size=4)
    np.testing.assert_allclose(naive, fused, rtol=1e-5)
    assert int(faux["tokens"]) == 13


@pytest.mark.parametrize("chunk_size", [3, 8, 1024])
def test_matches_naive_loss_and_aux(chunk_size):
    hidden, head, targets = _setup()
    naive, naux = cross_entropy_loss(
        jnp.einsum("bse,ev->bsv", hidden, head), targets)
    fused, faux = fused_cross_entropy(hidden, head, targets,
                                      chunk_size=chunk_size)
    np.testing.assert_allclose(naive, fused, rtol=1e-5)
    np.testing.assert_allclose(naux["accuracy"], faux["accuracy"], rtol=1e-6)
    assert int(naux["tokens"]) == int(faux["tokens"])


def test_masked_matches_naive():
    hidden, head, targets = _setup()
    mask = (jnp.arange(12)[None, :] < jnp.array([[5], [9]])).astype(
        jnp.float32)
    naive, _ = cross_entropy_loss(
        jnp.einsum("bse,ev->bsv", hidden, head), targets, mask)
    fused, faux = fused_cross_entropy(hidden, head, targets, mask,
                                      chunk_size=4)
    np.testing.assert_allclose(naive, fused, rtol=1e-5)
    assert int(faux["tokens"]) == 14


def test_grads_match_naive():
    hidden, head, targets = _setup()

    def naive_fn(h, w):
        loss, _ = cross_entropy_loss(
            jnp.einsum("bse,ev->bsv", h, w), targets)
        return loss

    def fused_fn(h, w):
        loss, _ = fused_cross_entropy(h, w, targets, chunk_size=6)
        return loss

    gn_h, gn_w = jax.grad(naive_fn, argnums=(0, 1))(hidden, head)
    gf_h, gf_w = jax.grad(fused_fn, argnums=(0, 1))(hidden, head)
    np.testing.assert_allclose(gn_h, gf_h, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(gn_w, gf_w, rtol=1e-4, atol=1e-6)


def test_trainer_default_loss_uses_fused_and_trains():
    # End-to-end: the Trainer's default objective must equal the explicit
    # logits objective on the same params/batch.
    import optax

    from kubetorch_tpu.parallel import MeshSpec
    from kubetorch_tpu.training import Trainer

    cfg = LlamaConfig.tiny()
    mesh = MeshSpec(fsdp=-1).build()
    tr = Trainer(cfg, mesh, optimizer=optax.sgd(0.1))
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (2, 17))
    batch = {"inputs": jnp.asarray(toks[:, :-1], jnp.int32),
             "targets": jnp.asarray(toks[:, 1:], jnp.int32)}
    logits = llama.forward(tr.state["params"], batch["inputs"], cfg)
    explicit, _ = cross_entropy_loss(logits, batch["targets"])
    m0 = tr.step(batch)
    np.testing.assert_allclose(float(m0["loss"]), float(explicit), rtol=1e-4)
    for _ in range(4):
        m = tr.step(batch)
    assert float(m["loss"]) < float(m0["loss"])


def test_streaming_and_recompute_backwards_agree():
    """The custom-VJP streaming backward must produce the same gradients
    as the checkpointed-recompute backward (and the naive path) — masked,
    padded, both argnums."""
    hidden, head, targets = _setup(batch=2, seq=13)   # pads at chunk 4
    mask = (jnp.arange(13)[None, :] < jnp.array([[6], [11]])).astype(
        jnp.float32)

    def fn(h, w, backward):
        loss, _ = fused_cross_entropy(h, w, targets, mask, chunk_size=4,
                                      backward=backward)
        return loss

    def naive_fn(h, w):
        loss, _ = cross_entropy_loss(
            jnp.einsum("bse,ev->bsv", h, w), targets, mask)
        return loss

    gs_h, gs_w = jax.grad(fn, argnums=(0, 1))(hidden, head, "streaming")
    gr_h, gr_w = jax.grad(fn, argnums=(0, 1))(hidden, head, "recompute")
    gn_h, gn_w = jax.grad(naive_fn, argnums=(0, 1))(hidden, head)
    np.testing.assert_allclose(gs_h, gr_h, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(gs_w, gr_w, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(gs_h, gn_h, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(gs_w, gn_w, rtol=1e-4, atol=1e-6)


def test_streaming_value_and_grad_aux():
    """value_and_grad(has_aux=True) — the Trainer's exact usage — works
    through the custom VJP and the aux metrics match the eval path."""
    hidden, head, targets = _setup()

    def fn(h):
        return fused_cross_entropy(h, head, targets, chunk_size=6)

    (loss, aux), g = jax.value_and_grad(fn, has_aux=True)(hidden)
    eval_loss, eval_aux = jax.jit(fn)(hidden)
    np.testing.assert_allclose(loss, eval_loss, rtol=1e-6)
    np.testing.assert_allclose(aux["accuracy"], eval_aux["accuracy"],
                               rtol=1e-6)
    assert g.shape == hidden.shape and jnp.isfinite(g).all()


def test_backward_arg_validated():
    hidden, head, targets = _setup()
    with pytest.raises(ValueError, match="backward"):
        fused_cross_entropy(hidden, head, targets, backward="magic")


def test_mask_gradient_matches_naive_both_backwards():
    """grad w.r.t. the mask must agree across streaming, recompute, and
    the naive logits path (the streaming VJP carries the per-token
    (logz − gold) term explicitly)."""
    hidden, head, targets = _setup()
    mask0 = jnp.ones((2, 12), jnp.float32)

    def naive_fn(m):
        loss, _ = cross_entropy_loss(
            jnp.einsum("bse,ev->bsv", hidden, head), targets, m)
        return loss

    def fn(m, backward):
        loss, _ = fused_cross_entropy(hidden, head, targets, m,
                                      chunk_size=4, backward=backward)
        return loss

    gn = jax.grad(naive_fn)(mask0)
    gs = jax.grad(fn)(mask0, "streaming")
    gr = jax.grad(fn)(mask0, "recompute")
    np.testing.assert_allclose(gs, gr, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(gs, gn, rtol=1e-4, atol=1e-6)


def test_frozen_head_skips_head_grad():
    """head_grad=False: hidden grads unchanged, head cotangent zero —
    the LoRA trainer's configuration."""
    hidden, head, targets = _setup()

    def fn(h, w, head_grad):
        loss, _ = fused_cross_entropy(h, w, targets, chunk_size=6,
                                      head_grad=head_grad)
        return loss

    g_h, g_w = jax.grad(fn, argnums=(0, 1))(hidden, head, True)
    f_h, f_w = jax.grad(fn, argnums=(0, 1))(hidden, head, False)
    np.testing.assert_allclose(g_h, f_h, rtol=1e-6)
    assert not np.asarray(f_w).any()
    assert np.asarray(g_w).any()
