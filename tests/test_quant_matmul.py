"""Pallas int8 weight-streaming matmul: parity, block picking, gating
(ops/quant_matmul.py — no reference analogue, owned serving compute)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubetorch_tpu.ops import quant_matmul


def _ref(x, w_q, scale):
    return (x.astype(jnp.float32)
            @ (w_q.astype(jnp.float32) * scale.reshape(1, -1)
               .astype(jnp.float32)))


@pytest.mark.level("unit")
@pytest.mark.parametrize("b,k,n", [(8, 256, 512), (64, 512, 1024),
                                   (16, 384, 256)])
def test_int8_matmul_parity(b, k, n):
    kx, kw = jax.random.split(jax.random.key(0))
    x = jax.random.normal(kx, (b, k), jnp.float32)
    w = jax.random.randint(kw, (k, n), -127, 128, jnp.int8)
    scale = jnp.abs(jax.random.normal(jax.random.key(2), (n,),
                                      jnp.float32)) * 0.01 + 1e-4
    got = quant_matmul.int8_matmul(x, w, scale, interpret=True)
    want = _ref(x, w, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)


@pytest.mark.level("unit")
def test_int8_matmul_bf16_matches_wload_semantics():
    """Kernel result ≈ the einsum path on dequantized weights (the exact
    computation llama._wload feeds decode) within bf16 tolerance."""
    b, k, n = 4, 128, 256
    x = jax.random.normal(jax.random.key(0), (b, k), jnp.bfloat16)
    w = jax.random.randint(jax.random.key(1), (k, n), -127, 128, jnp.int8)
    scale = jnp.full((n,), 0.01, jnp.bfloat16)
    got = quant_matmul.int8_matmul(x, w, scale, interpret=True)
    assert got.dtype == jnp.bfloat16
    wd = w.astype(jnp.bfloat16) * scale.reshape(1, -1)
    want = jnp.einsum("bk,kn->bn", x, wd)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=5e-2, atol=5e-1)


@pytest.mark.level("unit")
def test_int8_matmul_under_jit_and_block_sizes():
    b, k, n = 8, 256, 1024
    x = jax.random.normal(jax.random.key(0), (b, k), jnp.float32)
    w = jax.random.randint(jax.random.key(1), (k, n), -127, 128, jnp.int8)
    scale = jnp.full((n,), 0.02, jnp.float32)
    want = _ref(x, w, scale)
    for bn in (128, 256, 512):
        got = jax.jit(lambda a: quant_matmul.int8_matmul(
            a, w, scale, block_n=bn, interpret=True))(x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-2, atol=2e-2)


@pytest.mark.level("unit")
def test_pick_block_n_vmem_budget():
    # small K: biggest block
    assert quant_matmul.pick_block_n(64, 4096, 14336) == 512
    # the 8B down-projection (K=14336): 512 would blow the 16 MiB scoped
    # VMEM limit double-buffered, must drop to 256
    assert quant_matmul.pick_block_n(64, 14336, 4096) == 256
    # nothing divides N
    assert quant_matmul.pick_block_n(64, 512, 300) is None


@pytest.mark.level("unit")
def test_viability_gate():
    x = jnp.zeros((2, 1, 64), jnp.bfloat16)
    w8 = jnp.zeros((64, 128), jnp.int8)
    wf = jnp.zeros((64, 128), jnp.bfloat16)
    s = jnp.zeros((128,), jnp.bfloat16)
    # no scale / non-int8 weights never take the kernel
    assert not quant_matmul.decode_matmul_viable(x, w8, None)
    assert not quant_matmul.decode_matmul_viable(x, wf, s)
    # prefill-shaped activations (many tokens) stay on the einsum
    big = jnp.zeros((64, 128, 64), jnp.bfloat16)
    assert not quant_matmul.decode_matmul_viable(big, w8, s)
    # CPU backend (the test env) never takes the kernel: the decode path
    # must be identical with and without quantized params present
    assert not quant_matmul.decode_matmul_viable(x, w8, s)


@pytest.mark.level("unit")
def test_viability_gate_rejects_live_mesh():
    """Under a >1-device mesh the einsum path must win (an unpartitioned
    pallas call would force operand all-gathers)."""
    from kubetorch_tpu.parallel.mesh import MeshSpec, use_mesh

    x = jnp.zeros((2, 1, 64), jnp.bfloat16)
    w8 = jnp.zeros((64, 128), jnp.int8)
    s = jnp.zeros((128,), jnp.bfloat16)
    with use_mesh(MeshSpec(fsdp=-1).build()):
        assert not quant_matmul.decode_matmul_viable(x, w8, s)
