"""Ring attention, pipeline parallelism, and flash-attention tests on the
virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubetorch_tpu.models import LlamaConfig, llama
from kubetorch_tpu.ops.attention import dot_product_attention
from kubetorch_tpu.ops.flash_attention import flash_attention
from kubetorch_tpu.parallel import MeshSpec
from kubetorch_tpu.parallel.pipeline import pipeline_apply
from kubetorch_tpu.parallel.ring import ring_attention


def _qkv(B=2, S=64, Hq=4, Hkv=2, D=16, dtype=jnp.float32):
    return (jax.random.normal(jax.random.key(0), (B, S, Hq, D), dtype),
            jax.random.normal(jax.random.key(1), (B, S, Hkv, D), dtype),
            jax.random.normal(jax.random.key(2), (B, S, Hkv, D), dtype))


# ------------------------------------------------------------- ring
def test_ring_attention_matches_global():
    mesh = MeshSpec(sp=4, tp=2).build()
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-4, atol=1e-4)


def test_ring_attention_noncausal_and_grads():
    mesh = MeshSpec(sp=2, fsdp=4).build()
    q, k, v = _qkv(S=32)
    ref = dot_product_attention(q, k, v, causal=False)
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh, causal=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-4, atol=1e-4)
    g = jax.jit(jax.grad(
        lambda q: ring_attention(q, k, v, mesh).sum()))(q)
    gref = jax.jit(jax.grad(
        lambda q: dot_product_attention(q, k, v, causal=True).sum()))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref),
                               rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("causal", [
    True,
    pytest.param(False, marks=pytest.mark.skipif(
        jax.default_backend() == "cpu",
        reason="capability: the D=128 flash chunk engine's NON-causal ring "
               "pass drifts past the 2e-3 tolerance on XLA:CPU (the "
               "fused-softmax accumulation order differs from the TPU "
               "lowering; the causal variant and the D=16 ring stay in "
               "tolerance). Needs a TPU backend. Env-dependent since seed "
               "(ROADMAP tier-1 note)."))])
def test_ring_attention_flash_engine_matches_global(causal):
    """D=128 engages the flash chunk engine inside the ring — results and
    gradients must match global attention."""
    mesh = MeshSpec(sp=4, fsdp=2).build()
    q, k, v = _qkv(B=2, S=64, Hq=4, Hkv=2, D=128)
    ref = dot_product_attention(q, k, v, causal=causal)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-3, atol=2e-3)

    def ring_loss(q, k, v):
        o = ring_attention(q, k, v, mesh, causal=causal)
        return (o * jnp.sin(o)).sum()

    def ref_loss(q, k, v):
        o = dot_product_attention(q, k, v, causal=causal)
        return (o * jnp.sin(o)).sum()

    gr = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    gf = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b, name in zip(gr, gf, "q k v".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3, err_msg=name)


def test_ring_attention_flash_engine_with_tp_heads():
    mesh = MeshSpec(sp=2, tp=2, dp=2).build()
    q, k, v = _qkv(B=2, S=32, Hq=4, Hkv=4, D=128)
    ref = dot_product_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-3, atol=2e-3)


# ------------------------------------------------------------- flash
def test_flash_attention_interpret_matches_reference():
    q, k, v = _qkv(S=256, D=128)
    ref = dot_product_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("Hq,Hkv", [(4, 4), (4, 2), (8, 2)])
def test_flash_attention_backward_matches_reference(causal, Hq, Hkv):
    """The Pallas backward kernels (dq + dk/dv incl. GQA group folding) must
    match the XLA reference's gradients across multiple q/kv blocks."""
    q, k, v = _qkv(S=256, Hq=Hq, Hkv=Hkv, D=128)

    def flash_loss(q, k, v):
        out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
        return (out * jnp.cos(out)).sum()

    def ref_loss(q, k, v):
        out = dot_product_attention(q, k, v, causal=causal)
        return (out * jnp.cos(out)).sum()

    gf = jax.jit(jax.grad(flash_loss, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b, name in zip(gf, gr, "q k v".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3, err_msg=name)


def test_flash_attention_grads_under_jit_and_mixed_blocks():
    q, k, v = _qkv(S=256, Hq=4, Hkv=2, D=128)

    @jax.jit
    def g(q, k, v):
        return jax.grad(lambda q: flash_attention(
            q, k, v, causal=True, block_q=128, block_k=64).sum())(q)

    gref = jax.jit(jax.grad(lambda q: dot_product_attention(
        q, k, v, causal=True).sum()))(q)
    np.testing.assert_allclose(np.asarray(g(q, k, v)), np.asarray(gref),
                               rtol=2e-3, atol=2e-3)


def test_flash_attention_fallback_on_odd_shapes():
    q, k, v = _qkv(S=100, D=16)  # not tileable -> XLA path
    ref = dot_product_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-5)


# ------------------------------------------------------------- pipeline
def test_pipeline_apply_linear_stages():
    """4 stages each adding a distinct constant: output must see all four in
    order regardless of microbatching."""
    mesh = MeshSpec(pp=4, fsdp=2).build()
    weights = jnp.arange(1.0, 5.0).reshape(4, 1)   # [pp, 1]

    def stage_fn(w, h):
        return h * 2.0 + w[0]

    x = jnp.ones((8, 3))
    out = jax.jit(lambda p, x: pipeline_apply(stage_fn, p, x, mesh, 4))(
        weights, x)
    # sequential: (((1*2+1)*2+2)*2+3)*2+4 = 2*…
    expected = x
    for w in [1.0, 2.0, 3.0, 4.0]:
        expected = expected * 2.0 + w
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-6)


def test_llama_pipeline_matches_sequential():
    cfg = LlamaConfig.tiny(n_layers=4)
    mesh = MeshSpec(pp=2, fsdp=2, tp=2).build()
    params = llama.init(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32)
    ref = llama.forward(params, tokens, cfg)
    out = jax.jit(lambda p, t: llama.forward_pipeline(
        p, t, cfg, mesh, n_microbatches=2))(params, tokens)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-4, atol=2e-4)


def test_llama_pipeline_grads_flow():
    cfg = LlamaConfig.tiny(n_layers=4)
    mesh = MeshSpec(pp=2, fsdp=4).build()
    params = llama.init(jax.random.key(0), cfg)
    tokens = jnp.zeros((8, 8), jnp.int32)

    def loss(p):
        logits = llama.forward_pipeline(p, tokens, cfg, mesh,
                                        n_microbatches=2)
        return jnp.mean(logits ** 2)

    grads = jax.jit(jax.grad(loss))(params)
    flat = jax.tree.leaves(grads)
    assert all(bool(jnp.isfinite(g).all()) for g in flat)
    # every layer's weights received gradient (all stages trained)
    per_layer = jnp.abs(grads["layers"]["wq"]).sum(axis=(1, 2))
    assert bool((per_layer > 0).all()), per_layer


@pytest.mark.level("minimal")
def test_no_involuntary_remat_in_sharded_train_steps(capfd):
    """XLA's "[SPMD] Involuntary full rematerialization" warning means a
    sharding transition degraded to replicate-then-repartition — at scale
    that destroys the layout's perf. Treat any occurrence in the pipeline
    (pp×fsdp) or dense (dp×fsdp×tp) train step as a failure (VERDICT r1 #2:
    round 1's pipeline entry resharded every layer param this way)."""
    import optax

    from kubetorch_tpu.parallel import ShardingRules, use_mesh
    from kubetorch_tpu.training import (
        cross_entropy_loss,
        init_train_state,
        make_train_step,
    )

    cfg = LlamaConfig.tiny(n_layers=2)
    layouts = []

    pp_mesh = MeshSpec(pp=2, fsdp=4).build()
    pp_rules = ShardingRules.pipeline()

    def pp_loss(params, batch):
        logits = llama.forward_pipeline(
            params, batch["inputs"], cfg, pp_mesh, n_microbatches=2,
            rules=pp_rules)
        return cross_entropy_loss(logits, batch["targets"])

    layouts.append((pp_mesh, pp_rules, pp_loss, "pp=2,fsdp=4"))
    layouts.append((MeshSpec(dp=2, fsdp=2, tp=2).build(),
                    ShardingRules.default(), None, "dp=2,fsdp=2,tp=2"))

    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (8, 17))
    batch = {"inputs": jnp.asarray(toks[:, :-1], jnp.int32),
             "targets": jnp.asarray(toks[:, 1:], jnp.int32)}
    # The warnings under test are emitted at COMPILE time — a persistent-
    # cache hit would skip compilation and vacuously pass.
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        capfd.readouterr()
        for mesh, rules, loss_fn, label in layouts:
            optimizer = optax.adamw(1e-3)
            with use_mesh(mesh):
                state = init_train_state(
                    jax.random.key(0), cfg, mesh, optimizer, rules)
                step = make_train_step(cfg, optimizer, rules,
                                       loss_fn=loss_fn, mesh=mesh)
                state, metrics = step(state, batch)
                assert np.isfinite(float(jax.device_get(metrics["loss"])))
            err = capfd.readouterr().err
            assert "Involuntary full rematerialization" not in err, (
                f"{label}: XLA degraded a sharding transition:\n" +
                "\n".join(l for l in err.splitlines()
                          if "rematerialization" in l)[:2000])
    finally:
        jax.config.update("jax_enable_compilation_cache", True)
