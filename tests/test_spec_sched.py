"""ISSUE 14: speculative decoding as a scheduler citizen.

Four layers:

1. **Lookahead state machine** (stdlib): per-row k driven by the
   acceptance EMA — convergence BOTH directions, the k=1 probe path,
   and the occupancy cap's immediate clamp.
2. **Sim engine spec surface** (no jax): scripted per-row accept rates
   drive the same adaptation loop CPU-only; token output stays the
   pure function of (prompt, index), so spec-on ≡ spec-off identity is
   byte-assertable; the DecodeEngine occupancy throttle caps and lifts
   per-row lookahead against live occupancy; the shed check prices
   verify waste.
3. **Real rolling engine** (tiny CPU model): greedy token identity
   through the full composition the ctor used to reject — chunked
   prefill × speculation × shared prefixes × adaptation — plus
   park/resume with live draft context (export/import round-trips the
   haystack, carried token, k and EMA) and the kk-masked rejection
   helpers' full-accept semantics.
4. **Engine-path sampled spec**: temperature > 0 programs through the
   rolling engine's verify rounds reuse ``rejection_accept`` /
   ``residual_next`` (the shared math) under per-row kk masks.
"""

import threading
import time

import numpy as np
import pytest

from kubetorch_tpu.lookahead import (
    GROW_AT,
    PROBE_EVERY,
    LookaheadState,
)

ALPHA = 0.25


# ------------------------------------------------ 1. state machine
@pytest.mark.level("unit")
def test_lookahead_grows_on_accepting_rows():
    st = LookaheadState(8, k0=2, ema0=0.5)
    ks = []
    for _ in range(12):
        for _ in range(4):                      # 4 rounds per chunk
            st.observe(st.k, st.k, alpha=ALPHA)   # every draft lands
        ks.append(st.adapt(8))
    assert st.k == 8, ks
    assert st.ema > GROW_AT


@pytest.mark.level("unit")
def test_lookahead_collapses_on_random_rows_and_probes():
    st = LookaheadState(8)                      # optimistic start: k=8
    for _ in range(10):
        for _ in range(4):
            st.observe(1, st.k, alpha=ALPHA)      # nothing lands
        st.adapt(8)
    assert st.k == 1, st.k                      # settled at plain decode
    # at the floor there is no evidence; after PROBE_EVERY chunks the
    # machine probes k=2 once...
    st.floor_chunks = 0
    for i in range(PROBE_EVERY - 1):
        assert st.adapt(8) == 1, i
    assert st.adapt(8) == 2
    # ...and a still-random row returns to the floor
    for _ in range(4):
        st.observe(1, st.k, alpha=ALPHA)
    for _ in range(4):
        st.adapt(8)
    assert st.k == 1


@pytest.mark.level("unit")
def test_lookahead_regrows_from_floor_when_regime_changes():
    st = LookaheadState(8)
    for _ in range(16):
        for _ in range(4):
            st.observe(1, st.k, alpha=ALPHA)
        st.adapt(8)
    assert st.k <= 2        # at the floor (or on a probe chunk)
    # the conversation turned extractive: the probe's rounds land and
    # the row climbs back to k_max
    for _ in range(PROBE_EVERY + 20):
        st.observe(st.k, st.k, alpha=ALPHA)
        st.adapt(8)
    assert st.k == 8


@pytest.mark.level("unit")
def test_lookahead_cap_clamps_immediately_and_lifts():
    st = LookaheadState(8)                      # k = 8
    assert st.adapt(8, cap=1) == 1              # throttle bites NOW
    st.ema = 1.0
    assert st.adapt(8, cap=1) == 1              # held at the cap
    for _ in range(12):
        st.observe(st.k, st.k, alpha=ALPHA)
        st.adapt(8, cap=0)                      # cap lifted
    assert st.k == 8


# ---------------------------------------------- 2. sim engine surface
@pytest.mark.level("unit")
def test_sim_spec_identity_and_per_row_convergence():
    """Scripted mixed traffic: spec-on emits BYTE-IDENTICAL streams to
    spec-off (speculation changes pacing, never content) while per-row
    k converges both directions."""
    from kubetorch_tpu.serving.engine import SimRollingEngine

    def accept(prompt):
        return 0.9 if prompt[0] % 2 == 0 else 0.0

    sim = SimRollingEngine(max_slots=4, steps_per_call=8, spec_k=6,
                           spec_accept=accept)
    prompts = [[100, 1], [101, 1], [102, 1], [103, 1]]
    rids = [sim.submit(p, max_new_tokens=96) for p in prompts]
    out = {}
    while sim.pending:
        for rid, toks, done in sim.step():
            out.setdefault(rid, []).extend(toks)
    for rid, p in zip(rids, prompts):
        assert out[rid] == SimRollingEngine.expected_tokens(p, 96)
    # convergence at completion: extractive rows held k > 2,
    # adversarial rows settled at the k = 1 floor
    for rid, p in zip(rids, prompts):
        k_final = sim.spec_k_done[rid]
        if p[0] % 2 == 0:
            assert k_final > 2, (p, k_final)
        else:
            assert k_final == 1, (p, k_final)
    ss = sim.spec_stats
    assert ss["rounds"] > 0 and 0.0 < ss["accept_rate"] < 1.0
    assert ss["verify_waste"] > 0                # adversarial rows paid
    assert ss["tokens_per_pass"] > 1.0


@pytest.mark.level("unit")
def test_sim_spec_occupancy_throttle_caps_and_lifts():
    """The driver tick is the occupancy throttle: above the threshold
    every row's lookahead caps at 1 (compute-bound regime), and the cap
    lifts when occupancy falls back."""
    from kubetorch_tpu.serving.engine import (
        DecodeEngine,
        SimRollingEngine,
    )

    sim = SimRollingEngine(max_slots=2, steps_per_call=2, spec_k=6,
                           spec_accept=0.9, step_s=0.01)
    eng = DecodeEngine(sim, poll_s=0.002, spec_throttle=0.9)
    try:
        done = []

        def run(n):
            try:
                list(eng.generate({"prompt": [2, n],
                                   "max_new_tokens": n}))
                done.append(n)
            # teardown close() fails the still-live stream typed; the
            # thread must exit quietly either way
            except Exception:  # noqa: BLE001
                pass

        t1 = threading.Thread(target=run, args=(4000,), daemon=True)
        t2 = threading.Thread(target=run, args=(64,), daemon=True)
        t1.start()
        t2.start()
        # both rows live -> occupancy 1.0 >= 0.9 -> capped at 1
        deadline = time.time() + 10
        while sim.spec_cap != 1 and time.time() < deadline:
            time.sleep(0.005)
        assert sim.spec_cap == 1, "throttle never capped lookahead"
        while sim.spec_row_ks() != [1] * 2 and time.time() < deadline:
            time.sleep(0.005)
        assert sim.spec_row_ks() == [1, 1], sim.spec_row_ks()
        t2.join(30)
        assert done == [64]
        # one row left -> occupancy 0.5 < 0.9 -> cap lifts, the
        # high-accept survivor regrows
        while sim.spec_cap != 0 and time.time() < deadline:
            time.sleep(0.005)
        assert sim.spec_cap == 0, "throttle never lifted"
        while (not any(k > 2 for k in sim.spec_row_ks())
               and time.time() < deadline):
            time.sleep(0.005)
        assert any(k > 2 for k in sim.spec_row_ks()), sim.spec_row_ks()
    finally:
        eng.close()


@pytest.mark.level("unit")
def test_spec_counters_reach_prometheus():
    """The driver tick's delta publisher must land in the process
    metrics dict: ``record_engine`` bumps counters with ``+=`` behind
    the serving path's must-never-raise guard, so an event target
    missing from the ``_ENGINE`` seed is a SILENT KeyError — the k
    gauges publish while the round/emit/waste counters read 0 forever
    (the bug the live drive caught). Pins the seed-coverage invariant
    and the end-to-end publication."""
    from kubetorch_tpu.observability import prometheus as prom
    from kubetorch_tpu.serving.engine import (
        DecodeEngine,
        SimRollingEngine,
    )

    # every counter record_engine can bump must be pre-seeded
    missing = [m for m in prom._ENGINE_EVENTS.values()
               if m not in prom._ENGINE]
    assert not missing, missing

    before = prom.engine_metrics()
    sim = SimRollingEngine(max_slots=2, steps_per_call=4, spec_k=4,
                           spec_accept=0.5)
    eng = DecodeEngine(sim, poll_s=0.002)
    try:
        out = list(eng.generate({"prompt": [2, 5], "max_new_tokens": 32}))
        assert sum(len(f["tokens"]) for f in out) == 32
        deadline = time.time() + 10
        while (prom.engine_metrics()["engine_spec_rounds_total"]
               <= before["engine_spec_rounds_total"]
               and time.time() < deadline):
            time.sleep(0.005)
    finally:
        eng.close()
    after = prom.engine_metrics()
    for name in ("engine_spec_rounds_total", "engine_spec_emitted_total",
                 "engine_spec_drafted_total",
                 "engine_spec_verify_waste_total"):
        assert after[name] > before[name], name
    assert after["engine_spec_accept_rate"] > 0.0


@pytest.mark.level("unit")
def test_shed_check_prices_verify_waste(monkeypatch):
    """Speculation-aware admission: with rows free slower than they
    verify (k_mean high, tokens_per_pass ~1 — drafts not landing), the
    row-free estimate scales by the verify load and the program sheds;
    the same queue under well-landing speculation admits."""
    from kubetorch_tpu.exceptions import ServerOverloaded
    from kubetorch_tpu.serving.engine import (
        DecodeEngine,
        SimRollingEngine,
    )

    monkeypatch.setenv("KT_MAX_QUEUE_DELAY_S", "0.2")

    def make(stats):
        class Fixed(SimRollingEngine):
            @property
            def spec_stats(self):
                return dict(stats)

        return Fixed(max_slots=1, steps_per_call=8, spec_k=6,
                     spec_accept=0.0, step_s=0.02)

    base = {"rounds": 100, "emitted": 100, "tokens_per_pass": 1.0,
            "drafted": 500, "accepted": 0, "accept_rate": 0.0,
            "verify_waste": 500, "k_mean": 5.0, "k_cap": 6}
    # wasteful speculation: est_delay x (k_mean / tpp) = 5x -> shed
    def drain_quietly(engine, prog):
        try:
            list(engine.generate(prog))
        except Exception:  # noqa: BLE001 — teardown fails it typed
            pass

    sim = make(base)
    eng = DecodeEngine(sim, poll_s=0.002, max_waiting=0)
    try:
        th = threading.Thread(
            target=drain_quietly,
            args=(eng, {"prompt": [9, 9], "max_new_tokens": 4000}),
            daemon=True)
        th.start()
        deadline = time.time() + 10
        while sim.active_rows < 1 and time.time() < deadline:
            time.sleep(0.005)
        # (waiting 0 + 1 new) x ema_row_s(0.05) = 0.05s base estimate;
        # x5 verify factor = 0.25 > 0.2 -> typed shed with retry_after
        with pytest.raises(ServerOverloaded) as err:
            next(eng.generate({"prompt": [1], "max_new_tokens": 4}))
        assert err.value.retry_after
    finally:
        eng.close()
    # efficient speculation (tpp == k_mean): factor 1 -> 0.05 < 0.2 ->
    # the same program QUEUES instead of shedding. emitted/rounds must
    # AGREE with tokens_per_pass: the shed check prices from the
    # driver tick's delta EMA, not the reported lifetime ratio
    good = dict(base, emitted=500, tokens_per_pass=5.0, accepted=400,
                accept_rate=0.8, verify_waste=100)
    sim2 = make(good)
    eng2 = DecodeEngine(sim2, poll_s=0.002, max_waiting=0)
    try:
        th = threading.Thread(
            target=drain_quietly,
            args=(eng2, {"prompt": [9, 9], "max_new_tokens": 64}),
            daemon=True)
        th.start()
        deadline = time.time() + 10
        while sim2.active_rows < 1 and time.time() < deadline:
            time.sleep(0.005)
        frames = list(eng2.generate({"prompt": [1],
                                     "max_new_tokens": 4}))
        assert frames[-1]["done"]
    finally:
        eng2.close()


@pytest.mark.level("unit")
def test_sim_spec_park_resume_keeps_adaptation_state(tmp_path,
                                                     monkeypatch):
    """CPU-only park/resume: a parked spec session's lookahead + EMA
    ride the store blob and resume where they left off — the sim twin
    of the real engine's draft-context round-trip."""
    from kubetorch_tpu.data_store import client as client_mod
    from kubetorch_tpu.serving.engine import (
        DecodeEngine,
        SimRollingEngine,
    )

    monkeypatch.setattr(client_mod, "_LOCAL_STORE", tmp_path)
    monkeypatch.setattr(client_mod.DataStoreClient, "_default", None)
    sim = SimRollingEngine(max_slots=2, steps_per_call=4, spec_k=6,
                           spec_accept=0.9, step_s=0.005)
    eng = DecodeEngine(sim, poll_s=0.002)
    prompt = [2, 5]
    try:
        got: list = []
        parked = threading.Event()

        def run_session():
            for f in eng.generate({"prompt": prompt,
                                   "max_new_tokens": 512,
                                   "session_id": "spec-sess"}):
                if f.get("parked"):
                    parked.set()
                    return
                got.extend(f["tokens"])

        th = threading.Thread(target=run_session, daemon=True)
        th.start()
        deadline = time.time() + 20
        while len(got) < 24 and time.time() < deadline:
            time.sleep(0.002)
        # the live row has adapted upward by now (accept 0.9)
        live_ks = sim.spec_row_ks()
        assert live_ks and live_ks[0] > 2, live_ks
        assert eng.park("spec-sess") == 1
        th.join(10)
        assert parked.is_set()

        rest: list = []
        for f in eng.generate({"prompt": prompt, "max_new_tokens": 512,
                               "session_id": "spec-sess"}):
            if not rest:
                # restored row resumes AT its parked lookahead — not
                # back at the optimistic start with a cleared EMA
                ks = sim.spec_row_ks()
                assert ks and ks[0] == live_ks[0], (ks, live_ks)
            rest.extend(f["tokens"])
            if len(rest) >= 16:
                break
        expect = SimRollingEngine.expected_tokens(
            prompt, len(got) + len(rest))
        assert got + rest == expect, "resumed spec stream diverged"
        assert eng.stats()["spec_rounds"] > 0
    finally:
        eng.close()


# -------------------------------------------- 3. real rolling engine
@pytest.fixture(scope="module")
def model():
    import jax

    from kubetorch_tpu.models import LlamaConfig, llama

    cfg = LlamaConfig(vocab_size=256, embed_dim=64, n_layers=2,
                      n_heads=4, n_kv_heads=2, head_dim=16, mlp_dim=128,
                      remat=False, dtype="float32",
                      param_dtype="float32", max_seq_len=128)
    params = llama.init(jax.random.key(0), cfg)
    return params, cfg


@pytest.mark.level("minimal")
def test_spec_full_composition_token_identity(model):
    """The tentpole pinned on the real engine: chunked prefill x shared
    prefix x per-row adaptive speculation, mid-flight, greedy — token
    streams equal the plain engine's for every request."""
    from kubetorch_tpu.models.rolling import RollingGenerator

    params, cfg = model
    prefix = [(i * 3) % 40 + 2 for i in range(12)]
    long_p = [(i * 7) % 50 + 2 for i in range(40)]    # chunked (>16)
    outs = {}
    for name, kw in (("plain", {}),
                     ("spec", {"spec_k": 4, "steps_per_call": 2,
                               "prefill_chunk": 16})):
        eng = RollingGenerator(params, cfg, max_slots=3, **kw)
        pid = eng.register_prefix(list(prefix))
        r1 = eng.submit([7, 8, 9], max_new_tokens=10, prefix_id=pid)
        r2 = eng.submit(list(long_p), max_new_tokens=10)
        res: dict = {}
        # a few steps in, a third request joins the live batch
        for _ in range(2):
            for rid, toks, _ in eng.step():
                res.setdefault(rid, []).extend(toks)
        r3 = eng.submit([5, 4], max_new_tokens=10, prefix_id=pid)
        for rid, toks in eng.run().items():
            res.setdefault(rid, []).extend(toks)
        outs[name] = [res[r1], res[r2], res[r3]]
        if kw:
            assert eng.spec_stats["rounds"] > 0
    assert outs["plain"] == outs["spec"], outs


@pytest.mark.level("minimal")
def test_spec_export_import_resume_identity(model):
    """Park/resume with LIVE draft context: a spec row exported
    mid-generation and imported into a fresh same-geometry spec engine
    continues token-identical to an uninterrupted run, with its
    lookahead + EMA intact."""
    from kubetorch_tpu.models.rolling import RollingGenerator

    params, cfg = model
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    ref_eng = RollingGenerator(params, cfg, max_slots=2, spec_k=4,
                               steps_per_call=1)
    rr = ref_eng.submit(list(prompt), max_new_tokens=16)
    ref = ref_eng.run()[rr]

    eng_a = RollingGenerator(params, cfg, max_slots=2, spec_k=4,
                             steps_per_call=1)
    ra = eng_a.submit(list(prompt), max_new_tokens=16)
    eng_a.admit()
    got = []
    while len(got) < 6:
        for _, toks, _ in eng_a.decode_step():
            got.extend(toks)
    state = eng_a.export_row(ra, block_tokens=16)
    assert "spec_ctx" in state and "spec" in state
    # the carried token + haystack survive; the ctx tail past the
    # row's depth is zeroed (cross-tenant hygiene, like the KV planes)
    dpos = int(np.asarray(state["scalars"])[0])
    assert not np.asarray(state["spec_ctx"])[dpos:].any()
    eng_a.evict(ra)

    eng_b = RollingGenerator(params, cfg, max_slots=2, spec_k=4,
                             steps_per_call=1)
    rb = eng_b.import_row(state)
    slot_b = eng_b._slots[next(s for s, r in eng_b._slots.items()
                               if r.rid == rb)].slot
    st_b = eng_b._spec_state[slot_b]
    st_a = np.asarray(state["spec"])
    assert st_b.k == int(st_a[2])               # lookahead survived
    assert st_b.ema == pytest.approx(
        float(np.asarray(state["spec_ema"])[0]))
    rest = []
    while True:
        events = eng_b.decode_step()
        if not events:
            break
        for _, toks, done in events:
            rest.extend(toks)
        if any(done for _, _, done in events):
            break
    assert got + rest == ref, (got, rest, ref)


@pytest.mark.level("minimal")
def test_spec_export_cross_mode(model):
    """Plain export -> spec engine works (haystack rebuilt, first token
    from the exported logits — greedy identity holds); spec export ->
    plain engine raises typed (the next token is round-carried state a
    plain engine cannot resume)."""
    from kubetorch_tpu.models.rolling import RollingGenerator

    params, cfg = model
    prompt = [11, 3, 7, 2]
    ref_eng = RollingGenerator(params, cfg, max_slots=2)
    rr = ref_eng.submit(list(prompt), max_new_tokens=12)
    ref = ref_eng.run()[rr]

    plain = RollingGenerator(params, cfg, max_slots=2)
    rp = plain.submit(list(prompt), max_new_tokens=12)
    plain.admit()
    got = []
    while len(got) < 4:
        for _, toks, _ in plain.decode_step():
            got.extend(toks)
    state = plain.export_row(rp, block_tokens=16)
    plain.evict(rp)

    spec = RollingGenerator(params, cfg, max_slots=2, spec_k=4,
                            steps_per_call=1)
    spec.import_row(state)
    rest = []
    done_f = False
    while not done_f:
        for _, toks, done in spec.decode_step():
            rest.extend(toks)
            done_f = done_f or done
    assert got + rest == ref, (got, rest, ref)

    # the reverse direction refuses typed
    rs = spec.submit(list(prompt), max_new_tokens=12)
    spec.admit()
    spec.decode_step()
    spec_state = spec.export_row(rs, block_tokens=16)
    plain2 = RollingGenerator(params, cfg, max_slots=2)
    with pytest.raises(ValueError, match="speculative"):
        plain2.import_row(spec_state)


@pytest.mark.level("minimal")
def test_kk_masked_rejection_helpers(model):
    """Per-row kk masking inside a wider dispatch must reproduce the
    k = kk semantics exactly: acceptance never crosses kk - 1, and a
    row's FULL accept (acc == kk - 1) draws from the unmodified break
    distribution — no mass removed for the never-tested boundary
    draft."""
    import jax
    import jax.numpy as jnp

    from kubetorch_tpu.models.speculative import (
        rejection_accept,
        residual_next,
    )

    del model
    B, k, V = 3, 4, 8
    feed = jnp.array([[1, 2, 3, 4]] * B, jnp.int32)
    # point mass ON the draft at every position: the unmasked test
    # accepts everything it is allowed to
    probs = jnp.zeros((B, k, V))
    for i in range(k):
        tgt = [2, 3, 4, 5][i]
        probs = probs.at[:, i, tgt].set(1.0)
    kk = jnp.array([1, 2, 4], jnp.int32)
    acc = rejection_accept(probs, feed, jax.random.key(0), k=k, kk=kk)
    # each row's acceptance is exactly its own kk - 1 (full accept)
    assert list(np.asarray(acc)) == [0, 1, 3]
    nxt = residual_next(probs, feed, acc, jax.random.key(1), k=k, kk=kk)
    # full accept at the row's own boundary: the next token draws from
    # the break position's UNTOUCHED distribution (its point mass at
    # positions 0/1/3 -> tokens 2/3/5) — no mass removed for the
    # never-tested boundary draft
    assert list(np.asarray(nxt)) == [2, 3, 5]


@pytest.mark.level("minimal")
def test_sampled_spec_through_engine_rounds(model):
    """Satellite: the engine's sampled verify rounds run the shared
    rejection path (``rejection_accept``/``residual_next``) under
    per-row kk masks — mixed greedy+sampled traffic through the
    adaptive engine produces full-length streams and flips the sticky
    sampling executable."""
    from kubetorch_tpu.models.rolling import RollingGenerator

    params, cfg = model
    eng = RollingGenerator(params, cfg, max_slots=4, spec_k=4,
                           steps_per_call=2, top_k=4, seed=5)
    r_greedy = eng.submit([2, 4, 6], max_new_tokens=12)
    r_hot = eng.submit([2, 4, 6], max_new_tokens=12, temperature=0.8)
    res = eng.run()
    assert len(res[r_greedy]) == 12 and len(res[r_hot]) == 12
    assert eng._spec_sampling           # the sampled row upgraded it
    # greedy rows in a mixed batch stay greedy-identical
    plain = RollingGenerator(params, cfg, max_slots=4, top_k=4)
    rp = plain.submit([2, 4, 6], max_new_tokens=12)
    assert plain.run()[rp] == res[r_greedy]
