"""int8 block-quantized AdamW (training/quant_opt.py): convergence parity
with f32 optax.adamw, state-size accounting, jit/mesh compatibility."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubetorch_tpu.training.quant_opt import (
    _dequantize,
    _quantize,
    adamw_quant,
)


@pytest.mark.level("unit")
def test_quantize_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 512)).astype(np.float32))
    q, s = _quantize(x, 256)
    assert q.dtype == jnp.int8 and q.shape == x.shape
    assert s.shape == (4, 2)
    err = jnp.abs(_dequantize(q, s, 256) - x)
    # absmax/127 per block bounds the roundtrip error
    assert float(err.max()) <= float(jnp.abs(x).max()) / 127.0 + 1e-6


@pytest.mark.level("unit")
def test_indivisible_axis_falls_back_to_whole_axis_scale():
    x = jnp.linspace(-1, 1, 2 * 100).reshape(2, 100)
    q, s = _quantize(x, 256)
    assert s.shape == (2, 1)
    np.testing.assert_allclose(_dequantize(q, s, 256), x, atol=1 / 127 + 1e-6)


@pytest.mark.level("minimal")
def test_convergence_parity_with_f32_adamw():
    """Same tiny LM-ish regression trained with f32 adamw and int8-moment
    adamw: loss trajectories must track closely and reach the same basin
    (the bar bitsandbytes sets for 8-bit Adam)."""
    rng = np.random.default_rng(1)
    X = jnp.asarray(rng.normal(size=(256, 32)).astype(np.float32))
    w_true = jnp.asarray(rng.normal(size=(32, 8)).astype(np.float32))
    Y = X @ w_true + 0.01 * jnp.asarray(
        rng.normal(size=(256, 8)).astype(np.float32))

    def loss_fn(params):
        pred = jnp.tanh(X @ params["w1"]) @ params["w2"]
        return jnp.mean((pred - Y) ** 2)

    def train(opt, steps=150):
        params = {
            "w1": jnp.asarray(rng2.normal(size=(32, 64),
                                          scale=0.1).astype(np.float32)),
            "w2": jnp.asarray(rng2.normal(size=(64, 8),
                                          scale=0.1).astype(np.float32)),
        }
        state = opt.init(params)

        @jax.jit
        def step(params, state):
            loss, g = jax.value_and_grad(loss_fn)(params)
            upd, state = opt.update(g, state, params)
            return optax.apply_updates(params, upd), state, loss

        losses = []
        for _ in range(steps):
            params, state, loss = step(params, state)
            losses.append(float(loss))
        return losses

    rng2 = np.random.default_rng(2)
    ref = train(optax.adamw(1e-2, b1=0.9, b2=0.95, weight_decay=1e-4))
    rng2 = np.random.default_rng(2)   # identical init
    quant = train(adamw_quant(1e-2, b1=0.9, b2=0.95, weight_decay=1e-4,
                              block=64))
    assert quant[-1] < ref[0] * 0.05          # it actually converged
    assert quant[-1] < ref[-1] * 1.5 + 1e-3   # ...to the same basin
    # trajectories track: mean relative gap over the run stays small
    gaps = [abs(a - b) / max(b, 1e-6) for a, b in zip(quant, ref)]
    assert sum(gaps) / len(gaps) < 0.25, sum(gaps) / len(gaps)


@pytest.mark.level("minimal")
def test_moment_state_is_int8_and_small():
    params = {"w": jnp.zeros((128, 512), jnp.bfloat16)}
    opt = adamw_quant(1e-3, block=256)
    state = opt.init(params)
    inner = state[0]  # chain: (scale_by_quant_adam, decay, lr)
    leaves = jax.tree.leaves(inner.mu) + jax.tree.leaves(inner.nu)
    int8_bytes = sum(x.nbytes for x in leaves if x.dtype == jnp.int8)
    scale_bytes = sum(x.nbytes for x in leaves if x.dtype == jnp.float32)
    param_bytes = 128 * 512 * 4
    assert int8_bytes == 2 * 128 * 512          # both moments, 1 byte/elt
    assert scale_bytes <= param_bytes / 64      # block=256 → 1/256 + f32


@pytest.mark.level("minimal")
def test_trainer_runs_with_quant_adam_on_mesh():
    """End-to-end: the Trainer's sharded train step accepts the quantized
    optimizer (int8 state keeps param shapes, so shardings propagate)."""
    from kubetorch_tpu.models import LlamaConfig
    from kubetorch_tpu.parallel import MeshSpec
    from kubetorch_tpu.training import Trainer

    cfg = LlamaConfig(vocab_size=128, embed_dim=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, head_dim=16, mlp_dim=128, remat=False,
                      dtype="float32", param_dtype="float32",
                      max_seq_len=64)
    mesh = MeshSpec(fsdp=-1).build()
    trainer = Trainer(cfg, mesh, optimizer=adamw_quant(1e-3, block=64))
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (4, 33))
    batch = {"inputs": jnp.asarray(toks[:, :-1], jnp.int32),
             "targets": jnp.asarray(toks[:, 1:], jnp.int32)}
    m1 = trainer.step(batch)
    m2 = trainer.step(batch)
    assert np.isfinite(float(m1["loss"])) and np.isfinite(float(m2["loss"]))
    assert float(m2["loss"]) < float(m1["loss"]) + 1.0
