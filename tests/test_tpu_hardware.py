"""Real-TPU tier (SURVEY §4: the reference gates GPU tests on GPU node
pools; here ``KT_TPU_TESTS=1 pytest --level tpu`` gates on live TPU
hardware). Everything here runs the actual Pallas kernels / Mosaic
compiles, not interpret mode."""

import numpy as np
import pytest


def _on_tpu():
    import jax

    try:
        return jax.devices()[0].platform != "cpu"
    except Exception:
        return False


pytestmark = pytest.mark.level("tpu")


@pytest.fixture(scope="module", autouse=True)
def _require_tpu():
    if not _on_tpu():
        pytest.skip("no TPU backend available")


def test_flash_kernel_matches_xla_on_device():
    import jax
    import jax.numpy as jnp

    from kubetorch_tpu.ops.attention import dot_product_attention
    from kubetorch_tpu.ops.flash_attention import flash_attention

    B, S, H, Hkv, D = 2, 2048, 8, 4, 128
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, S, Hkv, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, S, Hkv, D), jnp.bfloat16)

    ref = np.asarray(dot_product_attention(q, k, v, causal=True),
                     np.float32)
    out = np.asarray(flash_attention(q, k, v, causal=True), np.float32)
    np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)


def test_flash_backward_matches_xla_on_device():
    import jax
    import jax.numpy as jnp

    from kubetorch_tpu.ops.attention import dot_product_attention
    from kubetorch_tpu.ops.flash_attention import flash_attention

    B, S, H, Hkv, D = 1, 2048, 4, 2, 128
    ks = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, S, Hkv, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, S, Hkv, D), jnp.bfloat16)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True).astype(
            jnp.float32).sum()

    def loss_ref(q, k, v):
        return dot_product_attention(q, k, v, causal=True).astype(
            jnp.float32).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=5e-2, atol=5e-2)


def test_int8_decode_on_device():
    import jax

    from kubetorch_tpu.models import LlamaConfig, llama
    from kubetorch_tpu.models.generate import Generator
    from kubetorch_tpu.models.quant import quantize_params

    cfg = LlamaConfig(vocab_size=4096, embed_dim=512, n_layers=4,
                      n_heads=8, n_kv_heads=4, head_dim=64, mlp_dim=2048,
                      remat=False, dtype="bfloat16",
                      param_dtype="bfloat16", max_seq_len=256)
    params = jax.jit(lambda key: llama.init(key, cfg))(jax.random.key(0))
    gen_fp = Generator(params, cfg)
    qparams = jax.jit(quantize_params)(params)
    gen_q = Generator(qparams, cfg)
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7]]
    out_fp = gen_fp.generate(prompts, max_new_tokens=16, temperature=0.0)
    out_q = gen_q.generate(prompts, max_new_tokens=16, temperature=0.0)
    assert all(len(o) == 16 for o in out_q)
    # weight-only int8 stays close to bf16 greedy: most tokens agree
    agree = sum(a == b for fp, qq in zip(out_fp, out_q)
                for a, b in zip(fp, qq))
    assert agree >= 24, (agree, out_fp, out_q)
    # the fused serving layout (wqkv/wgu single weight streams) is the
    # same math on concatenated columns; the wider contraction may tile
    # its reduction differently on device, so allow last-ulp argmax flips
    # on near-ties but require near-total greedy agreement
    from kubetorch_tpu.models.quant import fuse_decode_layers

    fused = dict(qparams)
    fused["layers"] = fuse_decode_layers(qparams["layers"])
    out_fused = Generator(fused, cfg).generate(
        prompts, max_new_tokens=16, temperature=0.0)
    agree_fused = sum(a == b for qq, ff in zip(out_q, out_fused)
                      for a, b in zip(qq, ff))
    assert agree_fused >= 30, (agree_fused, out_fused, out_q)


def test_train_step_throughput_sane():
    import jax
    import optax

    from kubetorch_tpu.models import LlamaConfig
    from kubetorch_tpu.parallel import MeshSpec
    from kubetorch_tpu.training import Trainer

    cfg = LlamaConfig(vocab_size=8192, embed_dim=1024, n_layers=6,
                      n_heads=8, n_kv_heads=4, head_dim=128, mlp_dim=4096,
                      tie_embeddings=True, remat=True, remat_policy="dots",
                      dtype="bfloat16", param_dtype="bfloat16")
    mesh = MeshSpec(fsdp=-1).build()
    trainer = Trainer(cfg, mesh, optimizer=optax.adamw(1e-4))
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (4, 1025))
    data = {"inputs": jax.numpy.asarray(toks[:, :-1], jax.numpy.int32),
            "targets": jax.numpy.asarray(toks[:, 1:], jax.numpy.int32)}
    result = trainer.benchmark(data, n_steps=5, warmup=2)
    assert np.isfinite(result["loss"])
    assert result["tokens_per_sec"] > 5_000, result


def test_rolling_matches_static_on_device():
    """The deferred-merge rolling decode (chunk cache + merged attention +
    per-layer einsum select) greedy-matches the static scan ON DEVICE —
    the CPU parity tests can't see Mosaic/XLA-TPU lowering differences in
    the merge path (r4: the serving engine's core invariant)."""
    import jax

    from kubetorch_tpu.models import LlamaConfig, llama
    from kubetorch_tpu.models.generate import Generator
    from kubetorch_tpu.models.quant import quantize_params
    from kubetorch_tpu.models.rolling import RollingGenerator

    cfg = LlamaConfig(vocab_size=4096, embed_dim=512, n_layers=4,
                      n_heads=8, n_kv_heads=4, head_dim=64, mlp_dim=2048,
                      remat=False, dtype="bfloat16",
                      param_dtype="bfloat16", max_seq_len=256)
    params = jax.jit(lambda key: llama.init(key, cfg))(jax.random.key(0))
    qparams = jax.jit(quantize_params)(params)

    prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [11, 22, 33, 44]]
    gen = Generator(qparams, cfg)
    iso = [gen.generate([p], max_new_tokens=12, temperature=0.0)[0]
           for p in prompts]

    eng = RollingGenerator(qparams, cfg, max_slots=4, steps_per_call=5,
                           admit_width=2)
    rids = [eng.submit(p, max_new_tokens=12) for p in prompts]
    out = eng.run()
    # The merged attention (two score blocks, one softmax) is the same
    # math as the static single-block path, but its einsums may tile
    # reductions differently on device — like the fused-layout check
    # above, allow last-ulp argmax flips on near-ties while requiring
    # near-total greedy agreement.
    assert all(len(out[rid]) == 12 for rid in rids)
    agree = sum(a == b for rid, expect in zip(rids, iso)
                for a, b in zip(out[rid], expect))
    assert agree >= 34, (agree, [out[r] for r in rids], iso)


def test_int8_kv_cache_on_device():
    """int8 KV cache (per-vector scales, bf16-fused dequant attention)
    greedy-agrees with the bf16 cache on device — the quantized-attention
    einsums take different tilings than CPU."""
    import jax

    from kubetorch_tpu.models import LlamaConfig, llama
    from kubetorch_tpu.models.generate import Generator

    cfg = LlamaConfig(vocab_size=4096, embed_dim=512, n_layers=4,
                      n_heads=8, n_kv_heads=4, head_dim=64, mlp_dim=2048,
                      remat=False, dtype="bfloat16",
                      param_dtype="bfloat16", max_seq_len=256)
    params = jax.jit(lambda key: llama.init(key, cfg))(jax.random.key(0))
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7]]
    ref = Generator(params, cfg).generate(
        prompts, max_new_tokens=16, temperature=0.0)
    q8 = Generator(params, cfg, kv_dtype="int8").generate(
        prompts, max_new_tokens=16, temperature=0.0)
    agree = sum(a == b for r, s in zip(ref, q8) for a, b in zip(r, s))
    assert agree >= 28, (agree, ref, q8)   # ≥87% of 32 tokens


def test_int8_grid_rolling_on_device():
    """The int8 SERVING grid (the bench's primary rolling config:
    quantized splice at admission, bf16 chunks quantized at the
    once-per-chunk merge, merged int8-grid attention) greedy-agrees with
    the int8 static scan on device."""
    import jax

    from kubetorch_tpu.models import LlamaConfig, llama
    from kubetorch_tpu.models.generate import Generator
    from kubetorch_tpu.models.quant import quantize_params
    from kubetorch_tpu.models.rolling import RollingGenerator

    cfg = LlamaConfig(vocab_size=4096, embed_dim=512, n_layers=4,
                      n_heads=8, n_kv_heads=4, head_dim=64, mlp_dim=2048,
                      remat=False, dtype="bfloat16",
                      param_dtype="bfloat16", max_seq_len=256)
    params = jax.jit(lambda key: llama.init(key, cfg))(jax.random.key(0))
    qparams = jax.jit(quantize_params)(params)

    prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [11, 22, 33, 44]]
    gen = Generator(qparams, cfg, kv_dtype="int8")
    iso = [gen.generate([p], max_new_tokens=12, temperature=0.0)[0]
           for p in prompts]

    eng = RollingGenerator(qparams, cfg, max_slots=4, steps_per_call=5,
                           admit_width=2, kv_dtype="int8")
    rids = [eng.submit(p, max_new_tokens=12) for p in prompts]
    out = eng.run()
    assert all(len(out[rid]) == 12 for rid in rids)
    # The two engines quantize at different moments (static: every write;
    # rolling: once per chunk merge, the live chunk stays bf16), so their
    # bf16 logits sit a different rounding away from near-ties and flips
    # chain down the row. What IS invariant: the first token (pure
    # admission-prefill + quantized splice — any splice corruption shows
    # here) and broad agreement (corruption would give ~random tokens).
    firsts = sum(out[rid][0] == expect[0]
                 for rid, expect in zip(rids, iso))
    assert firsts == len(rids), (firsts, [out[r] for r in rids], iso)
    agree = sum(a == b for rid, expect in zip(rids, iso)
                for a, b in zip(out[rid], expect))
    assert agree >= 22, (agree, [out[r] for r in rids], iso)


def test_spec_rolling_on_device():
    """Speculative continuous batching ON DEVICE (r5): verify rounds,
    per-slot accepted-prefix merges, and the device-resident draft
    context must reproduce the plain rolling engine's greedy stream —
    CPU parity can't see Mosaic lowering differences in the per-round
    merge path. Loopy traffic also pins that acceptance actually
    engages on hardware."""
    import jax

    from kubetorch_tpu.models import LlamaConfig, llama
    from kubetorch_tpu.models.generate import Generator
    from kubetorch_tpu.models.quant import quantize_params
    from kubetorch_tpu.models.rolling import RollingGenerator

    cfg = LlamaConfig(vocab_size=4096, embed_dim=512, n_layers=4,
                      n_heads=8, n_kv_heads=4, head_dim=64, mlp_dim=2048,
                      remat=False, dtype="bfloat16",
                      param_dtype="bfloat16", max_seq_len=512)
    params = jax.jit(lambda key: llama.init(key, cfg))(jax.random.key(0))
    qparams = jax.jit(quantize_params)(params)

    gen = Generator(qparams, cfg)
    warm = gen.generate([[5, 9, 13]], max_new_tokens=48,
                        temperature=0.0)[0]
    loopy = [5, 9, 13] + warm[:32]
    prompts = [loopy, [1, 2, 3, 4, 5], loopy[:20]]

    plain = RollingGenerator(qparams, cfg, max_slots=4, steps_per_call=4,
                             kv_dtype="int8")
    rid_p = [plain.submit(list(p), max_new_tokens=24) for p in prompts]
    out_p = plain.run()

    spec = RollingGenerator(qparams, cfg, max_slots=4, steps_per_call=2,
                            spec_k=6, spec_ngram=2, kv_dtype="int8")
    rid_s = [spec.submit(list(p), max_new_tokens=24) for p in prompts]
    out_s = spec.run()

    assert all(len(out_s[r]) == 24 for r in rid_s)
    # int8 per-round (spec) vs per-chunk (plain) quantization timing
    # allows near-tie flips, and one early flip desynchronizes the rest
    # of that row — tolerate ONE fully-desynced 24-token row (the other
    # int8 device rows hold a comparable ~2/3 bar for the same reason)
    agree = sum(a == b for rp, rs in zip(rid_p, rid_s)
                for a, b in zip(out_p[rp], out_s[rs]))
    assert agree >= 48, (agree, [out_p[r] for r in rid_p],
                         [out_s[r] for r in rid_s])
    # speculation must engage on the loopy rows
    assert spec.spec_stats["tokens_per_pass"] > 1.2, spec.spec_stats
