"""Persistent pipelined call channel: one WebSocket carries many calls,
up to ``depth`` in flight, FIFO execution per channel, opaque payloads
through the pod hop, per-call latency decomposition, and exception
rehydration with later chunks already in flight (ISSUE 2 acceptance).

Also covers the satellite work: ``StreamResult.cancel()`` must free the
worker slot and not leak the per-request queue, and the channel
lifecycle counters must surface on the pod's /metrics."""

import os
import time
from pathlib import Path

import pytest

import kubetorch_tpu as kt
from kubetorch_tpu.resources.callables.cls import Cls

ASSETS = Path(__file__).parent / "assets" / "summer"


@pytest.fixture(autouse=True, scope="module")
def _local_state(tmp_path_factory):
    state = tmp_path_factory.mktemp("ktlocal-channel")
    os.environ["KT_LOCAL_STATE"] = str(state)
    import kubetorch_tpu.provisioning.backend as backend

    backend._LOCAL_ROOT = state
    yield
    for record in backend.LocalBackend().list_services():
        backend.LocalBackend().teardown(record["service_name"], quiet=True)


@pytest.fixture(scope="module")
def engine():
    remote = Cls(root_path=str(ASSETS), import_path="summer",
                 callable_name="ChunkEngine", name="chunkengine")
    remote.to(kt.Compute(cpus="0.1"))
    yield remote
    remote.teardown()


@pytest.mark.level("minimal")
def test_channel_basic_call_and_timings(engine):
    with engine.channel(depth=1) as chan:
        out = chan.call(1001, method="step")
        assert out["i"] == 1001 and out["seq"][-1] == 1001
        # second call rides the SAME connection
        out2 = chan.call(1002, method="step")
        assert out2["seq"][-2:] == [1001, 1002]
        assert chan.connects == 1
        # decomposition present and sane: device covers the worker-side
        # execution; wall covers everything
        call = chan.submit(1004, method="step", kwargs={"delay": 0.05})
        call.result()
        t = call.timings
        for key in ("client_ser", "wire", "server_queue",
                    "worker_dispatch", "device", "wall"):
            assert key in t, f"missing stage {key}: {t}"
        assert t["device"] >= 50.0  # the 50 ms sleep is device time
        assert t["wall"] >= t["device"]


@pytest.mark.level("minimal")
def test_channel_fifo_order_under_pipelining(engine):
    """Chunks submitted pipelined at depth 3 must EXECUTE in submission
    order — a stateful engine's correctness depends on it."""
    with engine.channel(depth=3) as chan:
        # NO warm-up call: the first burst races the connect itself —
        # all three in-flight submits must share ONE socket (a second
        # socket would split the FIFO order across connections)
        ids = list(range(2001, 2011))
        calls = [chan.submit(i, method="step",
                             kwargs={"delay": 0.02}) for i in ids]
        results = [c.result(timeout=60) for c in calls]
        assert chan.connects == 1
    for k, res in enumerate(results):
        assert res["i"] == ids[k]
        # the engine's seq ends with exactly the ids submitted so far
        assert res["seq"][-(k + 1):] == ids[:k + 1], (k, res["seq"])


@pytest.mark.level("minimal")
def test_channel_pipelining_overlaps_wire_with_device(engine):
    """Depth 2 must keep chunk N+1 in flight while N is on device. On
    localhost the hidden cost (client serialize + RTT) is ~1 ms, far
    below host noise, so the proof is structural, not a wall-clock race:
    with real overlap, the SUM of per-call in-flight times exceeds the
    run's wall time (two calls share every wall second), and throughput
    sits near the device floor n*d."""
    n, d = 6, 0.15
    with engine.channel(depth=2) as chan:
        chan.call(3100, method="step")  # warm
        t0 = time.perf_counter()
        calls = [chan.submit(3101 + i, method="step",
                             kwargs={"delay": d}) for i in range(n)]
        for c in calls:
            c.result(timeout=60)
        pipe_wall = time.perf_counter() - t0
    in_flight = sum(c.timings["wall"] for c in calls) / 1e3
    assert in_flight > pipe_wall * 1.3, (in_flight, pipe_wall)
    # near the device floor: the per-chunk dispatch tax is hidden
    assert pipe_wall < n * d * 1.7, (pipe_wall, n * d)


@pytest.mark.level("minimal")
def test_channel_exception_with_next_chunk_in_flight(engine):
    """ISSUE 2 acceptance: an exception on chunk N rehydrates on N's
    handle while N+1 (already in flight) still executes and resolves —
    pipelining must not smear one chunk's failure across its neighbors."""
    with engine.channel(depth=2) as chan:
        c1 = chan.submit(4001, method="step", kwargs={"delay": 0.05})
        c2 = chan.submit(4002, method="step", kwargs={"boom": True})
        c3 = chan.submit(4003, method="step")
        assert c1.result(timeout=60)["i"] == 4001
        with pytest.raises(ValueError, match="chunk 4002 blew up"):
            c2.result(timeout=60)
        out3 = c3.result(timeout=60)
        assert out3["i"] == 4003
        # 4002 raised before mutating state: seq has 4001 then 4003
        assert out3["seq"][-2:] == [4001, 4003]


@pytest.mark.level("minimal")
def test_channel_concurrent_calls_multiplex(engine):
    """concurrent=True opts out of FIFO: three 0.4 s sleeps overlap in
    the worker instead of serializing."""
    with engine.channel(depth=4) as chan:
        t0 = time.perf_counter()
        calls = [chan.submit(method="pid_sleep", kwargs={"seconds": 0.4},
                             concurrent=True) for _ in range(3)]
        pids = {c.result(timeout=60) for c in calls}
        wall = time.perf_counter() - t0
    assert len(pids) == 1  # one worker process served all three
    assert wall < 1.1, wall  # 3 × 0.4 s serialized would be ≥ 1.2 s


@pytest.mark.level("minimal")
def test_channel_stream_and_pickle(engine):
    with engine.channel(depth=2) as chan:
        items = list(chan.submit(4, method="chunk_stream", stream=True)
                     .result(timeout=60))
        assert items == [{"i": i} for i in range(4)]
        # opaque pickle payload through the same channel
        out = chan.call(5001, method="step", ser="pickle")
        assert out["i"] == 5001
        # stream=True on a plain (non-generator) method: one-item stream,
        # matching the POST path's fallback — the result is never dropped
        one = list(chan.submit(5002, method="step",
                               stream=True).result(timeout=60))
        assert len(one) == 1 and one[0]["i"] == 5002


@pytest.mark.level("minimal")
def test_channel_reconnects_after_drop(engine):
    """ISSUE 9 tentpole: a dropped socket is a recovery event, not a
    failure event. The in-flight call SURVIVES — the channel re-dials
    and replays it by idempotency key, the server re-attaches the fresh
    socket to the still-running execution, and the caller never sees the
    drop. Reconnect is still counted on both ends, and the engine must
    have executed the call exactly once."""
    import asyncio

    from kubetorch_tpu.observability import prometheus as prom

    with engine.channel(depth=2) as chan:
        assert chan.call(6001, method="step")["i"] == 6001
        before = prom.serving_metrics()["serving_channel_reconnects_total"]
        # kill the socket under a call that is still in flight
        slow = chan.submit(6002, method="step", kwargs={"delay": 1.0})
        time.sleep(0.2)  # let it reach the server
        asyncio.run_coroutine_threadsafe(
            chan._ws.close(), chan._loop).result(5.0)
        # the call completes across the drop — transparent replay
        out = slow.result(timeout=30)
        assert out["i"] == 6002
        assert chan.replays >= 1
        out3 = chan.call(6003, method="step")
        assert out3["i"] == 6003
        # exactly once: the engine's seq saw 6002 a single time, in order
        assert out3["seq"][-3:] == [6001, 6002, 6003]
        assert chan.connects == 2
        after = prom.serving_metrics()["serving_channel_reconnects_total"]
        assert after == before + 1
    # the POD counts the re-dial too (X-KT-Channel-Reconnect header):
    # operators alert on the pod's /metrics, not the client's
    import httpx

    data = httpx.get(f"{engine.service_url()}/metrics", timeout=10).json()
    assert data.get("serving_channel_reconnects_total", 0) >= 1
    # ...and the replay counters surface next to the serving snapshot
    assert data.get("replay_attaches_total", 0) \
        + data.get("replay_hits_total", 0) >= 1


@pytest.mark.level("minimal")
def test_channel_interrupted_carries_call_ids(engine):
    """``replay=False`` keeps the old fail-fast contract: calls
    written-but-unacknowledged when the socket drops fail with the typed
    ChannelInterrupted whose ``call_ids`` name exactly the in-doubt
    submissions — so a caller replaying idempotent work by hand knows
    what to re-issue. (With the default ``replay=True`` the channel does
    that replay itself; see test_channel_reconnects_after_drop.)"""
    import asyncio

    from kubetorch_tpu.serving.channel import ChannelInterrupted

    with engine.channel(depth=3, replay=False) as chan:
        assert chan.call(6101, method="step")["i"] == 6101
        # two calls in flight when the socket dies
        c1 = chan.submit(6102, method="step", kwargs={"delay": 1.0})
        c2 = chan.submit(6103, method="step")
        time.sleep(0.2)
        asyncio.run_coroutine_threadsafe(
            chan._ws.close(), chan._loop).result(5.0)
        errors = []
        for call in (c1, c2):
            with pytest.raises(ChannelInterrupted) as err:
                call.result(timeout=30)
            errors.append(err.value)
        # both handles got the SAME interruption, naming BOTH cids
        assert set(errors[0].call_ids) == {c1.cid, c2.cid}
        assert errors[0].call_ids == errors[1].call_ids
        assert str(c1.cid) in str(errors[0])
        # the channel still works after the interruption
        assert chan.call(6104, method="step")["i"] == 6104


@pytest.mark.level("minimal")
def test_channel_metrics_surface_on_pod(engine):
    """Satellite: channel lifecycle counters + in-flight gauge + worker
    call counters (summed across worker processes like the restore
    snapshot) land on the pod's /metrics."""
    import httpx

    with engine.channel(depth=2) as chan:
        for i in range(3):
            chan.call(7001 + i, method="step")
    url = engine.service_url()
    data = httpx.get(f"{url}/metrics", timeout=10).json()
    assert data.get("serving_channel_calls_total", 0) >= 3
    assert data.get("serving_channel_connects_total", 0) >= 1
    assert data.get("serving_channel_inflight") == 0
    assert data.get("serving_worker_calls_total", 0) >= 3
    assert data.get("serving_worker_exec_seconds_total", 0) > 0
    # prometheus exposition carries the le-labeled stage histograms
    text = httpx.get(f"{url}/metrics?format=prometheus", timeout=10).text
    assert "kubetorch_serving_channel_calls_total" in text
    assert 'kubetorch_serving_call_device_seconds_bucket' in text
    assert 'le="+Inf"' in text
    # and NO duplicate samples: a (name, labels) pair appearing twice
    # makes Prometheus reject the WHOLE scrape (the flat merged dict and
    # the histogram series must use disjoint names)
    samples = [line.split(" ")[0] for line in text.splitlines()
               if line and not line.startswith("#")]
    dupes = {s for s in samples if samples.count(s) > 1}
    assert not dupes, f"duplicate exposition samples: {sorted(dupes)}"


@pytest.mark.level("minimal")
def test_client_standalone_exposition():
    """A client process (no pod server) can render its own serving
    counters + stage histograms via serving_samples — and that standalone
    exposition must be duplicate-free too."""
    from kubetorch_tpu.observability import prometheus as prom

    prom.record_call_stages({"client_ser": 0.001, "wire": 0.004})
    text = prom.render(list(prom.serving_samples({"client": "bench"})))
    assert "kubetorch_serving_call_wire_seconds_bucket" in text
    assert "kubetorch_serving_call_wire_seconds_sum" in text
    assert "kubetorch_serving_channel_connects_total" in text
    samples = [line.split(" ")[0] for line in text.splitlines()
               if line and not line.startswith("#")]
    dupes = {s for s in samples if samples.count(s) > 1}
    assert not dupes, f"duplicate exposition samples: {sorted(dupes)}"


@pytest.mark.level("minimal")
def test_send_drops_calls_failed_before_shipping(engine):
    """Reconnect race guard: an outbox entry whose call is already gone
    (failed/resolved before the writer reached it) must NOT be shipped —
    the server would execute a call the client reported as failed,
    double-stepping a stateful engine on resubmit. The writer skips dead
    cids before even dialing."""
    with engine.channel(depth=2) as chan:
        # cid 999 was never registered (the moral equivalent of a call
        # wiped by _fail_pending): the writer must skip it pre-dial
        chan._enqueue(999)
        time.sleep(0.3)  # let the writer drain it
        assert chan.connects == 0, "dead-call envelope dialed a socket"
        # a live call still connects and executes normally
        assert chan.call(9001, method="step")["i"] == 9001
        assert chan.connects == 1


@pytest.mark.level("minimal")
def test_post_path_unchanged_and_timed(engine):
    """The plain POST path still works next to the channel and now
    carries the server-side decomposition header."""
    out = engine.step(8001)
    assert out["i"] == 8001
    import httpx

    from kubetorch_tpu import serialization as ser
    from kubetorch_tpu.serving.http_client import sync_client

    resp = sync_client().post(
        f"{engine.service_url()}/ChunkEngine/step",
        content=ser.dumps({"args": [8002], "kwargs": {}}),
        headers={ser.HEADER: "json"})
    assert resp.status_code == 200
    import json

    t = json.loads(resp.headers["X-KT-Timing"])
    assert t["server_s"] > 0 and "exec_s" in t


@pytest.mark.level("minimal")
def test_stream_cancel_frees_slot_and_queue():
    """Satellite: cancelling a streamed call mid-iteration must free the
    worker slot AND drop the per-request routing entries (futures /
    stream queue) once the terminal lands — a leak here grows without
    bound on a long-lived serving pod."""
    from kubetorch_tpu import serialization
    from kubetorch_tpu.serving.process_pool import ProcessPool

    pool = ProcessPool(num_procs=1)
    pool.start()
    try:
        pool.setup_all(root_path=str(ASSETS), import_path="summer",
                       name="ChunkEngine", callable_type="cls")
        body = serialization.dumps(
            {"args": [100000], "kwargs": {"delay": 0.005}}, "json")
        resp = pool.call(body, "json", method="chunk_stream", timeout=30)
        stream = resp["stream"]
        it = iter(stream)
        next(it)
        next(it)
        stream.cancel()
        # drain to the terminal: must arrive promptly
        t0 = time.perf_counter()
        leftover = sum(1 for _ in it)
        assert time.perf_counter() - t0 < 10
        assert leftover < 1000
        assert stream.terminal.get("ok")
        # NO leaked routing state once the terminal landed
        assert pool._streams == {}, "per-request stream queue leaked"
        assert pool._futures == {}, "response future leaked"
        assert pool._collect == {}
        # the worker slot is free: a fresh call executes normally
        body2 = serialization.dumps({"args": [1], "kwargs": {}}, "json")
        resp2 = pool.call(body2, "json", method="step", timeout=30)
        assert resp2["ok"]
        assert pool._streams == {} and pool._futures == {}
    finally:
        pool.stop()
