"""ktsan: the concurrency-sanitizer gate (tier-1).

Four layers:

1. **Static fixtures** (``tests/assets/san/``): a seeded two-lock
   inversion the static side must flag (KT010), await/blocking-under-
   sync-lock shapes (KT008), double-acquire shapes (KT009), a clean
   module producing zero findings, and a dynamic-only inversion the
   static side must NOT flag.
2. **Dynamic runtime**: in-process install/uninstall with held-set and
   edge recording; a subprocess driving the hidden inversion under
   ``KT_SAN=1`` whose atexit report the merger unions into a detected
   cycle; the event-loop stall detector.
3. **The gate**: the whole package analyzes in <10 s with zero
   non-baselined findings and no lock-order cycles, twice, emitting
   byte-identical JSON (determinism).
4. **Dynamic smoke**: a server-heavy test subset runs green under
   ``KT_SAN=1`` with pod + test-process reports dumped and merged, and
   the thread-leak guard (a subprocess pytest with a deliberately
   leaked non-daemon thread) fails with the rendered message.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import pytest

from kubetorch_tpu.analysis import san
from kubetorch_tpu.analysis import baseline as baseline_mod
from kubetorch_tpu.analysis.engine import LintConfig, load_lint_config
from kubetorch_tpu.analysis.lockgraph import (
    DYNAMIC,
    LockGraph,
    LockInfo,
    Witness,
)
from kubetorch_tpu.analysis.san import (
    SAN_RULE_DOCS,
    build_static,
    collect_lock_defs,
    cycle_findings,
    run_san,
)

REPO = Path(__file__).resolve().parent.parent
ASSETS = Path(__file__).resolve().parent / "assets" / "san"

pytestmark = pytest.mark.level("unit")


def san_path(path: Path):
    cfg = LintConfig(root=REPO, paths=[str(path)])
    return run_san(cfg, static_only=True, apply_baseline=False)


def names_on_lines(path: Path, findings):
    src = path.read_text().splitlines()
    out = set()
    for f in findings:
        for i in range(f.line - 1, -1, -1):
            line = src[i]
            stripped = line.strip()
            if stripped.startswith(("def ", "async def ")) and (
                    line.startswith(("def ", "async def ", "    def ",
                                     "    async def "))):
                out.add(stripped.split("(")[0].split()[-1])
                break
    return out


# ------------------------------------------------------------ lock model
def test_lock_identity_resolution(tmp_path):
    mod = tmp_path / "m.py"
    mod.write_text(textwrap.dedent("""
        import threading
        import asyncio

        GLOBAL_LOCK = threading.Lock()

        class C:
            _class_lock = threading.Lock()

            def __init__(self):
                self._lock = threading.RLock()
                self._wake = threading.Condition(self._lock)
                self._alock = asyncio.Lock()
    """))
    cfg = LintConfig(root=tmp_path, paths=[str(mod)])
    from kubetorch_tpu.analysis.engine import FileContext

    ctx = FileContext(mod, "m.py", mod.read_text(), cfg)
    locks = collect_lock_defs(ctx)
    assert locks.module_names["GLOBAL_LOCK"] == "m.py::GLOBAL_LOCK"
    assert ("C", "_class_lock") in locks.class_attrs
    assert locks.infos["m.py::C._lock"].kind == "RLock"
    assert locks.infos["m.py::C._alock"].kind == "AsyncLock"
    # Condition(self._lock) aliases to the wrapped lock's identity
    assert locks.aliases["m.py::C._wake"] == "m.py::C._lock"


# --------------------------------------------------------- static: KT010
def test_static_catches_seeded_inversion():
    result = san_path(ASSETS / "inversion_static.py")
    kt010 = [f for f in result.findings if f.rule == "KT010"]
    assert len(kt010) == 1, [str(f) for f in result.findings]
    f = kt010[0]
    assert "Inverted._a" in f.snippet and "Inverted._b" in f.snippet
    # the rendered path names both witnessing sites
    assert "in Inverted.fwd [static]" in f.message
    assert "in Inverted.rev [static]" in f.message
    # the consistently-ordered pair is NOT in any cycle
    assert "ConsistentPair" not in f.message
    assert not any("ConsistentPair" in f.snippet for f in result.findings)


def test_static_silent_on_dynamic_only_fixture():
    result = san_path(ASSETS / "dyn_inversion.py")
    assert result.findings == [], [str(f) for f in result.findings]


# --------------------------------------------------------- static: KT008
def test_kt008_fixture_shapes():
    path = ASSETS / "await_under_lock.py"
    result = san_path(path)
    kt008 = [f for f in result.findings if f.rule == "KT008"]
    hit = names_on_lines(path, kt008)
    expected = {"tp_await_under_lock", "tp_sleep_under_lock",
                "tp_blocking_via_callee", "tp_event_wait_under_lock"}
    assert expected <= hit, f"KT008 missed: {expected - hit}"
    forbidden = {"fp_await_no_lock", "fp_async_lock_across_await",
                 "fp_condition_wait", "fp_suppressed", "_sleep_inside"}
    assert not (hit & forbidden), f"KT008 false positives: {hit & forbidden}"
    assert {f.rule for f in result.findings} == {"KT008"}


# --------------------------------------------------------- static: KT009
def test_kt009_fixture_shapes():
    path = ASSETS / "double_acquire.py"
    result = san_path(path)
    kt009 = [f for f in result.findings if f.rule == "KT009"]
    hit = names_on_lines(path, kt009)
    assert {"tp_via_locked_callee", "tp_direct_nest"} <= hit
    forbidden = {"fp_good_locked_callee", "fp_rlock_reentry",
                 "_append_locked"}
    assert not (hit & forbidden), f"KT009 false positives: {hit & forbidden}"


def test_clean_fixture_zero_findings():
    result = san_path(ASSETS / "clean.py")
    assert result.findings == [], [str(f) for f in result.findings]


# ----------------------------------------------------- cycles + baseline
def test_cycle_finding_baseline_is_line_shift_proof():
    g = LockGraph()
    g.add_lock(LockInfo("m.py::A._a", "Lock", "m.py", 3))
    g.add_lock(LockInfo("m.py::A._b", "Lock", "m.py", 4))
    g.add_edge("m.py::A._a", "m.py::A._b", Witness("m.py", 10, "fwd"))
    g.add_edge("m.py::A._b", "m.py::A._a", Witness("m.py", 20, "rev"))
    findings = cycle_findings(g)
    assert len(findings) == 1
    base = {baseline_mod.finding_key(findings[0]): 1}
    # shift every line: the signature snippet (no line numbers) matches
    g2 = LockGraph()
    g2.add_lock(LockInfo("m.py::A._a", "Lock", "m.py", 30))
    g2.add_lock(LockInfo("m.py::A._b", "Lock", "m.py", 40))
    g2.add_edge("m.py::A._a", "m.py::A._b", Witness("m.py", 100, "fwd"))
    g2.add_edge("m.py::A._b", "m.py::A._a", Witness("m.py", 200, "rev"))
    new, matched = baseline_mod.split(cycle_findings(g2), base)
    assert new == [] and len(matched) == 1


def test_cycle_canonicalization_and_merge():
    g = LockGraph()
    g.add_edge("b", "a", Witness("x.py", 1, "f"))
    g.add_edge("a", "b", Witness("x.py", 2, "g"))
    assert g.cycles() == [["a", "b"]]          # rotated: smallest first
    other = LockGraph()
    other.add_edge("b", "c", Witness("y.py", 3, "h", DYNAMIC))
    g.merge(other)
    assert ("b", "c") in g.edges
    # self-edges are dropped at the graph layer (KT009's job)
    g.add_edge("a", "a", Witness("x.py", 9, "z"))
    assert ("a", "a") not in g.edges


# ----------------------------------------------------------- determinism
def test_two_static_runs_emit_identical_json():
    cfg = load_lint_config(REPO)
    r1 = run_san(cfg, static_only=True, apply_baseline=False)
    r2 = run_san(cfg, static_only=True, apply_baseline=False)
    j1 = json.dumps({"findings": [f.to_dict() for f in r1.findings],
                     "graph": r1.graph.to_dict()}, sort_keys=True)
    j2 = json.dumps({"findings": [f.to_dict() for f in r2.findings],
                     "graph": r2.graph.to_dict()}, sort_keys=True)
    assert j1 == j2


# ------------------------------------------------------------------ gate
def test_gate_package_clean_under_10s():
    t0 = time.perf_counter()
    cfg = load_lint_config(REPO)
    result = run_san(cfg, static_only=True)
    elapsed = time.perf_counter() - t0
    assert elapsed < 10.0, f"san took {elapsed:.1f}s (budget 10s)"
    assert not result.errors, result.errors
    assert result.cycles == [], (
        "lock-order cycle(s) in the package:\n"
        + "\n".join(result.graph.render_cycle(c) for c in result.cycles))
    assert result.findings == [], (
        "non-baselined san findings:\n"
        + "\n".join(str(f) for f in result.findings))
    # the two audited orderings stay on the graph as documentation
    edges = set(result.graph.edges)
    assert ("kubetorch_tpu/serving/engine.py::DecodeEngine._offload_lock",
            "kubetorch_tpu/serving/engine.py::DecodeEngine._wake") in edges


def test_rule_docs_cover_san_rules():
    assert set(SAN_RULE_DOCS) == {"KT008", "KT009", "KT010"}
    for code, (name, doc) in SAN_RULE_DOCS.items():
        assert name and len(doc) > 40


# ------------------------------------------------------- dynamic runtime
@pytest.fixture
def san_runtime(monkeypatch):
    """In-process install with guaranteed uninstall. Skips (rather than
    double-installs) when the session itself runs under KT_SAN=1."""
    if san.active():
        yield san
        return
    assert san.install()
    try:
        yield san
    finally:
        san.uninstall()


def test_dynamic_records_inversion_in_process(san_runtime):
    sys.path.insert(0, str(ASSETS))
    try:
        import dyn_inversion
        dyn_inversion.drive()
    finally:
        sys.path.remove(str(ASSETS))
    g = san.runtime_graph()
    ab = ("tests/assets/san/dyn_inversion.py:16",
          "tests/assets/san/dyn_inversion.py:17")
    assert ab in g.edges and (ab[1], ab[0]) in g.edges
    wit = g.edges[ab][0]
    assert wit.kind == DYNAMIC and wit.path.endswith("dyn_inversion.py")
    assert [c for c in g.cycles()
            if "dyn_inversion" in c[0]], "runtime cycle not detected"


def test_dynamic_rlock_reentry_no_false_edge(san_runtime):
    # exercise the repo's own Condition-over-Lock idiom via the fixture
    sys.path.insert(0, str(ASSETS))
    try:
        import clean
        d = clean.Disciplined()
        d.update("k", 1)
        d.wait_for_rows(timeout=0.01)
        d.snapshot_then_work()
    finally:
        sys.path.remove(str(ASSETS))
    g = san.runtime_graph()
    # meta->data observed; data->meta never
    meta = "tests/assets/san/clean.py:14"
    data = "tests/assets/san/clean.py:15"
    assert (meta, data) in g.edges
    assert (data, meta) not in g.edges


def test_worker_graph_piggyback_roundtrip(san_runtime):
    """Workers can't dump on the pod's os._exit: their graph ships on
    call responses and merges into the pod's runtime graph. Pin the
    snapshot-if-changed contract (None when nothing grew) and the
    ingest merge."""
    sys.path.insert(0, str(ASSETS))
    try:
        import dyn_inversion
        dyn_inversion.drive()
    finally:
        sys.path.remove(str(ASSETS))
    snap = san.snapshot_graph_if_changed()
    assert snap is not None and snap["edges"], "graph snapshot empty"
    assert san.snapshot_graph_if_changed() is None  # unchanged → no ship
    before = len(san.runtime_graph().edges)
    assert san.ingest_graph(snap)                   # pod-side merge
    assert len(san.runtime_graph().edges) >= before


def test_stall_detector(san_runtime):
    import asyncio

    async def main():
        time.sleep((san._rt.stall_ms + 60) / 1000.0)

    before = san._rt.stall_count
    asyncio.run(main())
    assert san._rt.stall_count > before


def test_subprocess_report_merge_detects_planted_cycle(tmp_path):
    """The full dynamic pipeline: a subprocess drives the hidden
    inversion under KT_SAN=1, its atexit hook dumps the report, the
    merger unions it with the static graph, cycle detection fires."""
    env = dict(os.environ, KT_SAN="1", KT_SAN_DIR=str(tmp_path),
               PYTHONPATH=str(REPO))
    code = textwrap.dedent(f"""
        import sys
        from kubetorch_tpu.analysis import san
        assert san.install_from_env()
        sys.path.insert(0, {str(ASSETS)!r})
        import dyn_inversion
        dyn_inversion.drive()
    """)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    reports = list(tmp_path.glob("san-*.json"))
    assert len(reports) == 1, "atexit dump missing"
    data = json.loads(reports[0].read_text())
    assert data["acquires"] >= 4
    report = san.session_check(str(tmp_path), include_static=False)
    assert report is not None and "lock-order cycle" in report
    assert "dyn_inversion.py" in report


# --------------------------------------------------------- dynamic smoke
def test_dynamic_smoke_server_heavy_under_san(tmp_path):
    """A server-heavy test subset (real pod subprocess + channel) runs
    green under KT_SAN=1: the instrumented session must not deadlock,
    must dump reports from the test process AND the pod, and the merged
    session graph must be cycle-free."""
    env = dict(os.environ, KT_SAN="1", KT_SAN_DIR=str(tmp_path),
               JAX_PLATFORMS="cpu")
    env.pop("KT_SAN_LEAKS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "pytest",
         "tests/test_call_channel.py", "-q", "-p", "no:cacheprovider",
         "-k", "basic or fifo or concurrent or reconnects"],
        cwd=str(REPO), env=env, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    reports = [json.loads(p.read_text())
               for p in tmp_path.glob("san-*.json")]
    assert reports, "no dynamic reports dumped"
    total_locks = sum(len(r["graph"]["locks"]) for r in reports)
    assert total_locks > 0, "instrumented session tracked no repo locks"
    # the channel client's documented submit->calls lock order shows up
    merged, n = san.merge_reports(str(tmp_path))
    assert n == len(reports)
    assert merged.cycles() == [], "\n".join(
        merged.render_cycle(c) for c in merged.cycles())


def test_thread_leak_guard_catches_leak(tmp_path):
    """The conftest module-scoped guard fails a module that leaves a
    non-daemon thread behind, naming the thread."""
    conftest = tmp_path / "conftest.py"
    conftest.write_text(textwrap.dedent(f"""
        import importlib.util

        _spec = importlib.util.spec_from_file_location(
            "repo_conftest", {str(REPO / 'tests' / 'conftest.py')!r})
        _repo_conftest = importlib.util.module_from_spec(_spec)
        _spec.loader.exec_module(_repo_conftest)
        _thread_leak_guard = _repo_conftest._thread_leak_guard
    """))
    leaky = tmp_path / "test_leaky.py"
    leaky.write_text(textwrap.dedent("""
        import threading
        import time

        def test_leaves_thread():
            threading.Thread(target=time.sleep, args=(5.0,),
                             name="kt-leaky-driver").start()
    """))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("KT_SAN_LEAKS", None)
    env["PYTHONPATH"] = str(REPO)
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", str(leaky), "-q",
         "-p", "no:cacheprovider"],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=180)
    assert proc.returncode != 0, "leak guard did not fail the module"
    assert "kt-leaky-driver" in proc.stdout
    assert "non-daemon thread(s) leaked" in proc.stdout


# ------------------------------------------------- surfaced-defect fixes
def test_every_merged_metric_group_is_registered():
    """Regression for the defect the instrumented session surfaced: pod
    `/metrics` merged the "resilience" (and now "san") group without
    registering it in ``_PROC_GROUPS`` — the first recorded tick turned
    every scrape into a 500 KeyError, exactly during a preemption drain.
    Statically pin that every literal group name passed to
    ``_merge_proc_snapshot`` is registered."""
    import ast as ast_mod

    src = (REPO / "kubetorch_tpu" / "serving" / "server.py").read_text()
    tree = ast_mod.parse(src)
    groups, used = set(), set()
    for node in ast_mod.walk(tree):
        if isinstance(node, ast_mod.Assign):
            for tgt in node.targets:
                if getattr(tgt, "id", "") == "_PROC_GROUPS" and \
                        isinstance(node.value, ast_mod.Dict):
                    groups = {k.value for k in node.value.keys
                              if isinstance(k, ast_mod.Constant)}
        if isinstance(node, ast_mod.Call) and isinstance(
                node.func, ast_mod.Attribute) and \
                node.func.attr == "_merge_proc_snapshot" and node.args:
            first = node.args[0]
            if isinstance(first, ast_mod.Constant):
                used.add(first.value)
    assert groups, "_PROC_GROUPS not found"
    missing = used - groups
    assert not missing, (
        f"groups merged by h_metrics but not registered in "
        f"_PROC_GROUPS (scrape 500s on first tick): {missing}")
    assert {"resilience", "san"} <= groups


# --------------------------------------------------------------- the CLI
def test_cli_san_json_and_exit_codes(tmp_path):
    env = dict(os.environ, PYTHONPATH=str(REPO))
    proc = subprocess.run(
        [sys.executable, "-m", "kubetorch_tpu.cli", "san",
         "--static-only", "--json"],
        cwd=str(REPO), env=env, capture_output=True, text=True,
        timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert data["findings"] == [] and data["cycles"] == []
    assert data["locks"] > 20 and data["edges"] >= 2
    # a seeded inversion makes the CLI exit 1 with the rendered cycle
    proc = subprocess.run(
        [sys.executable, "-m", "kubetorch_tpu.cli", "san",
         "--static-only", "--no-baseline",
         str(ASSETS / "inversion_static.py")],
        cwd=str(REPO), env=env, capture_output=True, text=True,
        timeout=120)
    assert proc.returncode == 1
    assert "lock-order cycle" in proc.stdout
