"""Real-cluster profile (VERDICT r4 missing #1): the K8s backend against
any reachable API server — kind/k3s compatible.

Every other K8s test in this repo runs against ``tests/fake_k8s.py``;
this module is the bridge to a real control plane for the first user
with a cluster. Gated on ``KT_K8S_TESTS=1`` (and a reachable kubeconfig /
in-cluster service account); the full pod-server launch additionally
needs ``KT_K8S_IMAGE`` naming a pullable kubetorch-tpu pod image.

One-command kind setup (README "Real-cluster test profile"):

    kind create cluster --name kt && \
    docker build -t kubetorch-tpu:dev -f release/Dockerfile --build-arg JAX_EXTRA=cpu . && \
    kind load docker-image kubetorch-tpu:dev --name kt && \
    KT_K8S_TESTS=1 KT_K8S_IMAGE=kubetorch-tpu:dev \
        pytest tests/test_k8s_real.py --level release -q
"""

import os
import time
import uuid

import pytest

pytestmark = [
    pytest.mark.level("release"),
    pytest.mark.skipif(os.environ.get("KT_K8S_TESTS") != "1",
                       reason="KT_K8S_TESTS=1 not set (real-cluster "
                              "profile; see module docstring)"),
]


def _scoped(client, namespace):
    """Same server/auth, default namespace pinned to the test namespace
    (teardown and launch resolve objects through the client default)."""
    import copy

    scoped = copy.copy(client)
    scoped.namespace = namespace
    return scoped


@pytest.fixture(scope="module")
def client():
    from kubetorch_tpu.provisioning.k8s_client import K8sClient

    try:
        c = K8sClient.from_env()
        c.list("Pod", "default")
    except Exception as exc:  # pragma: no cover - env-dependent
        pytest.skip(f"no reachable cluster: {exc}")
    return c


@pytest.fixture(scope="module")
def namespace(client):
    ns = f"kt-test-{uuid.uuid4().hex[:8]}"
    client.apply({"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": ns}})
    yield ns
    try:
        client.delete("Namespace", ns, namespace=None)
    except Exception:
        pass


def test_client_crud_roundtrip(client, namespace):
    """apply → get → list-by-label → delete against the real API server
    (the plumbing every backend operation rides)."""
    name = "kt-probe"
    client.apply({
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": {"kubetorch.com/service": name}},
        "data": {"k": "v"},
    })
    got = client.get("ConfigMap", name, namespace)
    assert got["data"] == {"k": "v"}
    listed = client.list("ConfigMap", namespace,
                         label_selector=f"kubetorch.com/service={name}")
    assert any(o["metadata"]["name"] == name for o in listed)
    client.delete("ConfigMap", name, namespace)
    time.sleep(0.5)
    listed = client.list("ConfigMap", namespace,
                         label_selector=f"kubetorch.com/service={name}")
    assert not [o for o in listed if o["metadata"]["name"] == name]


def test_manifests_apply_and_cascade_teardown(client, namespace):
    """The backend's generated Deployment+Service manifests are accepted
    by a real API server and the teardown cascade removes them — schema
    compatibility, which the fake cannot prove."""
    from kubetorch_tpu.provisioning.k8s_backend import K8sBackend
    from kubetorch_tpu.provisioning.manifests import build_manifests
    from kubetorch_tpu.resources.compute.compute import Compute

    name = f"kt-mf-{uuid.uuid4().hex[:6]}"
    compute = Compute(cpus="100m", memory="64Mi", namespace=namespace)
    for manifest in build_manifests(name, compute, {"KT_TEST": "1"}):
        client.apply(manifest)
    assert client.get("Deployment", name, namespace)
    assert client.get("Service", name, namespace)
    ns_client = _scoped(client, namespace)
    backend = K8sBackend(client=ns_client)
    backend.teardown(name)
    deadline = time.time() + 30
    while time.time() < deadline:
        left = client.list(
            "Deployment", namespace,
            label_selector=f"kubetorch.com/service={name}")
        if not left:
            break
        time.sleep(1.0)
    assert not left, f"teardown left objects: {left}"


@pytest.mark.skipif(not os.environ.get("KT_K8S_IMAGE"),
                    reason="KT_K8S_IMAGE not set (pullable pod image "
                           "needed for the full launch test)")
def test_full_launch_ready_logs_teardown(client, namespace):
    """backend.launch → real pods Ready (pod server's /ready probe) →
    logs → teardown. The closest local-cluster analogue of the
    reference's CI-on-GKE suites."""
    import kubetorch_tpu as kt
    from kubetorch_tpu.provisioning.k8s_backend import K8sBackend

    name = f"kt-e2e-{uuid.uuid4().hex[:6]}"
    backend = K8sBackend(client=_scoped(client, namespace))
    compute = kt.Compute(
        cpus="200m", memory="512Mi", namespace=namespace,
        image=kt.Image(image_id=os.environ["KT_K8S_IMAGE"]))
    record = backend.launch(
        name,
        module_env={},
        compute_dict=compute.to_dict(),
        module_meta={"import_path": "none"},
        launch_timeout=int(os.environ.get("KT_K8S_LAUNCH_TIMEOUT", "180")),
        launch_id="real1",
    )
    try:
        assert record["service_name"] == name
        pods = client.list(
            "Pod", namespace,
            label_selector=f"kubetorch.com/service={name}")
        assert pods, "no pods after ready launch"
        logs = client.pod_logs(pods[0]["metadata"]["name"], namespace)
        assert isinstance(logs, str)
    finally:
        backend.teardown(name, quiet=True)
