"""Deployment-shape coverage (VERDICT r1 weak #8: "one test-asset
project"): multi-file packages, stateful + async classes, bad-import
failure, and live code edits through reload — the reference's asset
variety (async_summer, kv_store, multi-module projects, failure cases)
as local-backend e2e deploys."""

import os
from pathlib import Path

import pytest

import kubetorch_tpu as kt
from kubetorch_tpu.exceptions import StartupError
from kubetorch_tpu.resources.callables.cls import Cls
from kubetorch_tpu.resources.callables.fn import Fn

ASSETS = Path(__file__).parent / "assets"


@pytest.fixture(autouse=True, scope="module")
def _local_state(tmp_path_factory):
    state = tmp_path_factory.mktemp("ktlocal-shapes")
    os.environ["KT_LOCAL_STATE"] = str(state)
    import kubetorch_tpu.provisioning.backend as backend

    backend._LOCAL_ROOT = state
    yield
    for record in backend.LocalBackend().list_services():
        backend.LocalBackend().teardown(record["service_name"], quiet=True)


@pytest.mark.level("minimal")
def test_multifile_package_deploys_and_live_edits(tmp_path, monkeypatch):
    """An entry module importing a sibling package: the whole tree must
    deploy, and a one-submodule edit must flow through reload_code's
    delta sync."""
    import shutil

    import kubetorch_tpu.data_store.client as ds_client
    from kubetorch_tpu.data_store.client import DataStoreClient

    # route code through the store so reload actually re-syncs
    monkeypatch.setenv("KT_LOCAL_STORE", str(tmp_path / "store"))
    monkeypatch.setattr(ds_client, "_LOCAL_STORE", tmp_path / "store")
    monkeypatch.setenv("KT_CODE_SYNC", "always")
    monkeypatch.setenv("KT_CODE_DEST", str(tmp_path / "pod-code"))
    monkeypatch.setattr(DataStoreClient, "_default", None)

    proj = tmp_path / "proj"
    shutil.copytree(ASSETS / "multipkg", proj)
    remote = Fn(root_path=str(proj), import_path="entry",
                callable_name="tenfold", name="multipkg")
    remote.to(kt.Compute(cpus="0.1"))
    try:
        assert remote(4) == 40
        (proj / "mathkit" / "util.py").write_text("FACTOR = 100\n")
        remote.reload_code()
        assert remote(4) == 400  # the edited submodule was re-synced
    finally:
        remote.teardown()


@pytest.mark.level("minimal")
def test_stateful_kv_class_with_async_method():
    remote = Cls(root_path=str(ASSETS / "statefulsvc"),
                 import_path="kvstore", callable_name="KVStore",
                 name="kvsvc",
                 init_args={"args": [], "kwargs": {"namespace": "ns1"}})
    remote.to(kt.Compute(cpus="0.1"))
    try:
        assert remote.put("a", {"x": 1}) == 1
        assert remote.put("b", 2) == 2
        assert remote.get("a") == {"x": 1}
        assert remote.keys() == ["a", "b"]
        assert remote.delete("a") is True
        assert remote.get("a", "gone") == "gone"
        # async method awaited on the worker loop
        assert remote.slow_sum([1, 2, 3]) == {"namespace": "ns1", "sum": 6}
    finally:
        remote.teardown()


@pytest.mark.level("minimal")
def test_bad_import_fails_launch_fast_with_reason():
    remote = Fn(root_path=str(ASSETS / "badimport"), import_path="broken",
                callable_name="unreachable", name="badimport")
    import time

    t0 = time.monotonic()
    with pytest.raises(StartupError,
                       match="a_module_that_does_not_exist"):
        remote.to(kt.Compute(cpus="0.1", launch_timeout=60))
    assert time.monotonic() - t0 < 30, "burned the launch timeout"
