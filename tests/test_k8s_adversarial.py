"""Adversarial K8s API semantics against the fake (VERDICT r3 #6): 409
conflicts with client retry, admission rejection as a typed launch error,
and watch resourceVersion expiry (410 Gone) relisting through the event
watcher. These are the behaviors a real API server exercises that a
happy-path fake never would."""

import pytest

from kubetorch_tpu.exceptions import (
    AdmissionRejectedError,
    ConflictError,
    WatchExpiredError,
)
from kubetorch_tpu.provisioning.k8s_backend import K8sBackend
from kubetorch_tpu.provisioning.k8s_client import K8sClient
from kubetorch_tpu.resources.compute.compute import Compute

from fake_k8s import FakeK8s


@pytest.fixture()
def fake(monkeypatch):
    server = FakeK8s()
    monkeypatch.setenv("KT_READY_POLL", "0.05")
    monkeypatch.delenv("KT_CONTROLLER_URL", raising=False)
    yield server
    server.close()


@pytest.fixture()
def client(fake):
    return K8sClient(fake.url, namespace="default")


def _manifest(name):
    return {"apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"replicas": 1, "template": {"metadata": {"labels": {
                "kubetorch.com/service": name}}}}}


@pytest.mark.level("unit")
def test_apply_retries_conflicts_then_succeeds(fake, client):
    fake.conflict_next(2)
    out = client.apply(_manifest("svc-409"))
    assert out["metadata"]["name"] == "svc-409"
    assert fake.conflict_hits == 2
    assert ("default", "deployments", "svc-409") in fake.objects


@pytest.mark.level("unit")
def test_apply_conflict_exhaustion_raises_typed(fake, client):
    fake.conflict_next(10)
    with pytest.raises(ConflictError, match="409"):
        client.apply(_manifest("svc-409b"), conflict_retries=2)
    assert fake.conflict_hits == 3  # initial + 2 retries


@pytest.mark.level("unit")
def test_admission_rejection_surfaces_as_typed_launch_error(fake):
    backend = K8sBackend(client=K8sClient(fake.url, namespace="default"))
    fake.reject_admission("svc-adm", "TPU quota exceeded in queue ml")
    with pytest.raises(AdmissionRejectedError,
                       match="TPU quota exceeded in queue ml"):
        backend.launch(
            "svc-adm",
            module_env={"KT_MODULE": "svc-adm"},
            compute_dict=Compute(cpus="1").to_dict(),
            module_meta={"import_path": "svc:fn"},
            launch_timeout=5,
            launch_id="gen1",
        )


@pytest.mark.level("unit")
def test_watch_410_raises_watch_expired(fake, client):
    fake.expire_watches()
    with pytest.raises(WatchExpiredError, match="410"):
        list(client.watch("Event", "default", resource_version="1"))


@pytest.mark.level("unit")
def test_watch_replays_events_after_resource_version(fake, client):
    fake.push_event("e1", uid="u1", message="first")
    items, version = client.list_with_version("Event", "default")
    assert len(items) == 1
    fake.push_event("e2", uid="u2", message="second")
    got = list(client.watch("Event", "default", resource_version=version))
    assert [o["metadata"]["uid"] for _, o in got] == ["u2"]


@pytest.mark.level("unit")
def test_event_watcher_survives_expiry_and_never_duplicates(fake, client):
    """watch_once drives list→watch; an expiry surfaces typed (the loop
    relists on it), and the catch-up list after expiry pushes each event
    exactly once."""
    from kubetorch_tpu.controller.event_watcher import EventWatcher
    from kubetorch_tpu.observability.log_sink import LogSink

    sink = LogSink()
    watcher = EventWatcher(sink, k8s_client=client,
                           namespace="default",
                           list_services=lambda: [])
    fake.push_event("e1", uid="u1", message="one")
    assert watcher.watch_once(timeout_seconds=1) == 1

    # expiry mid-cycle: list catches up (pushes the new event), then the
    # stream 410s and the typed error propagates for the loop to handle
    fake.push_event("e2", uid="u2", message="two")
    fake.expire_watches()
    with pytest.raises(WatchExpiredError):
        watcher.watch_once(timeout_seconds=1)
    # the pre-expiry list already delivered e2 — a fresh cycle must not
    # re-push it
    assert watcher.watch_once(timeout_seconds=1) == 0
    lines = [e["line"] for e in sink.query({"job": "kubetorch-events"})]
    assert len(lines) == 2
    assert len([ln for ln in lines if "two" in ln]) == 1


@pytest.mark.level("unit")
def test_watcher_loop_treats_expiry_as_routine(fake, client):
    """The loop-level contract: WatchExpiredError does NOT count toward
    the watch-failure fallback that degrades to polling."""
    from kubetorch_tpu.controller.event_watcher import EventWatcher
    from kubetorch_tpu.observability.log_sink import LogSink

    watcher = EventWatcher(LogSink(), k8s_client=client,
                           namespace="default", interval=0.01,
                           list_services=lambda: [])
    import threading

    fake.expire_watches()
    stop = threading.Event()
    t = threading.Thread(target=watcher._loop, args=(stop,), daemon=True)
    t.start()
    import time

    time.sleep(0.5)
    stop.set()
    t.join(5)
    assert watcher._watch_ok, "410 expiry degraded the watcher to polling"
