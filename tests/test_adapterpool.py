"""PR 16: multi-tenant LoRA serving — device-resident adapter pool.

Three layers, all CPU:

1. **Pool policy units**: clock-injected :class:`AdapterPool` — miss →
   background fetch → driver-tick install, LRU eviction of COLD
   residents only, sticky load errors, Retry-After ETA floors.
2. **Engine integration** (DecodeEngine over SimRollingEngine): a
   residency miss sheds typed with a Retry-After while the load runs in
   the background; prefix cache entries are keyed by adapter NAME and
   die with the adapter's eviction (slot recycling must never serve one
   tenant's prefix KV to another); park/evict-adapter/resume round-trips
   byte-identical with the name binding riding the state blob.
3. **Tenant telemetry + SLO**: per-adapter counters flow through
   telemetry frames, and a per-adapter SLO objective breaches
   independently of the fleet-wide one.
"""

import threading
import time

import pytest

from kubetorch_tpu.exceptions import ServerOverloaded
from kubetorch_tpu.serving.adapterpool import AdapterPool
from kubetorch_tpu.serving.engine import (
    DecodeEngine,
    GenerationProgram,
    SimRollingEngine,
)

pytestmark = pytest.mark.level("unit")


def _wait(cond, timeout=10.0, what="condition"):
    deadline = time.time() + timeout
    while not cond():
        assert time.time() < deadline, f"timed out waiting for {what}"
        time.sleep(0.005)


def _until_resident(fn, timeout=15.0):
    """Retry ``fn`` through residency-miss sheds — the client loop a
    typed Retry-After asks for."""
    deadline = time.time() + timeout
    while True:
        try:
            return fn()
        except ServerOverloaded as exc:
            assert exc.retry_after and exc.retry_after > 0
            assert time.time() < deadline, "adapter never became resident"
            time.sleep(0.01)


@pytest.fixture()
def local_store(tmp_path, monkeypatch):
    from kubetorch_tpu.data_store import client as client_mod

    root = tmp_path / "store"
    monkeypatch.setenv("KT_LOCAL_STORE", str(root))
    monkeypatch.setattr(client_mod, "_LOCAL_STORE", root)
    monkeypatch.setattr(client_mod.DataStoreClient, "_default", None)
    yield root


# ------------------------------------------------------- pool policy
def test_pool_miss_load_install_and_lru():
    t = [0.0]
    applied = []
    evicted = []
    pool = AdapterPool(2, lambda n: f"tree-{n}",
                       lambda s, tr: applied.append((s, tr)),
                       clock=lambda: t[0], load_ema_alpha=0.5,
                       load_seed_s=0.2,
                       on_evict=lambda n, s: evicted.append((n, s)))
    assert pool.slot_of("a") is None
    assert pool.request("a") is None          # miss → background fetch
    assert pool.misses == 1
    _wait(lambda: pool.stats()["staged"] == 1, what="staged fetch")
    assert pool.has_staged()
    assert pool.slot_of("a") is None          # staged ≠ resident
    assert pool.admit_ready() == ["a"]
    assert applied == [(0, "tree-a")]
    assert pool.request("a") == 0 and pool.misses == 1
    # frozen clock ⇒ measured load time 0 ⇒ EMA halves toward 0
    assert pool.stats()["load_ema_s"] == pytest.approx(0.1)
    assert pool.acquire("a") == 0             # pin for a live row
    with pytest.raises(KeyError, match="not resident"):
        pool.acquire("ghost")
    pool.request("b")
    _wait(lambda: pool.stats()["staged"] == 1, what="staged fetch")
    assert pool.admit_ready() == ["b"]        # free slot 1, no evict
    assert pool.resident() == {"a": 0, "b": 1}
    # every slot pinned: a staged adapter WAITS (never rip weights out
    # from under a decoding row)
    pool.acquire("b")
    pool.request("c")
    _wait(lambda: pool.stats()["staged"] == 1, what="staged fetch")
    assert pool.admit_ready() == []
    assert pool.stats()["staged"] == 1 and evicted == []
    # b goes cold first → it is the LRU victim; the on_evict hook sees
    # the (name, slot) so the engine can drop name-keyed prefixes
    t[0] = 1.0
    pool.release("b")
    t[0] = 2.0
    pool.release("a")
    assert pool.admit_ready() == ["c"]
    assert evicted == [("b", 1)]
    assert pool.resident() == {"a": 0, "c": 1}
    assert pool.evictions == 1 and pool.loads == 3
    # explicit evict refuses a pinned adapter, drops a cold one
    pool.acquire("a")
    assert pool.evict("a") is False
    pool.release("a")
    assert pool.evict("a") is True
    assert evicted[-1] == ("a", 0)


def test_pool_load_failure_is_sticky_until_next_request():
    fail = {"on": True}

    def loader(name):
        if fail["on"]:
            raise RuntimeError("store down")
        return "tree"

    pool = AdapterPool(1, loader, lambda s, tr: None,
                       load_ema_alpha=0.5, load_seed_s=0.2)
    assert pool.request("x") is None
    _wait(lambda: pool.load_error("x"), what="sticky load error")
    assert "RuntimeError: store down" in pool.load_error("x")
    fail["on"] = False
    assert pool.request("x") is None          # clears error, refetches
    _wait(lambda: pool.stats()["staged"] == 1, what="staged refetch")
    assert pool.load_error("x") is None
    assert pool.admit_ready() == ["x"]
    assert pool.slot_of("x") == 0


def test_pool_load_eta_tracks_inflight_and_floors():
    gate = threading.Event()
    t = [0.0]
    pool = AdapterPool(1, lambda n: gate.wait(10) and "tr",
                       lambda s, tr: None, clock=lambda: t[0],
                       load_ema_alpha=0.5, load_seed_s=0.3)
    assert pool.load_eta() == pytest.approx(0.3)
    pool.request("x")
    t[0] = 0.1                                # 0.1s into the fetch
    assert pool.load_eta("x") == pytest.approx(0.2)
    t[0] = 5.0                                # overdue: floor, never <= 0
    assert pool.load_eta("x") == pytest.approx(0.05)
    gate.set()


# ------------------------------------------- engine integration (sim)
def _mk_engine(pool_slots=2, sim_slots=2, load_delay=0.0, **sim_kw):
    sim_kw.setdefault("steps_per_call", 4)
    sim_kw.setdefault("step_s", 0.002)
    sim = SimRollingEngine(max_slots=sim_slots,
                           adapter_slots=pool_slots, **sim_kw)

    def loader(name):
        if load_delay:
            time.sleep(load_delay)
        return {"adapter": name}

    pool = AdapterPool(pool_slots, loader, sim.load_adapter_slot,
                       load_ema_alpha=0.5, load_seed_s=0.1)
    eng = DecodeEngine(sim, poll_s=0.002, adapter_pool=pool)
    return eng, sim, pool


def test_program_adapter_wire_validation():
    prog = GenerationProgram.from_wire(
        {"prompt": [1, 2], "max_new_tokens": 4, "adapter": "tenant-a"})
    assert prog.adapter == "tenant-a" and prog.adapter_id == -1
    with pytest.raises(ValueError, match="non-empty string name"):
        GenerationProgram.from_wire(
            {"prompt": [1], "max_new_tokens": 2, "adapter": ""})
    with pytest.raises(ValueError, match="not both"):
        GenerationProgram.from_wire(
            {"prompt": [1], "max_new_tokens": 2, "adapter": "a",
             "adapter_id": 1})


def test_named_adapter_without_pool_fails_typed():
    eng = DecodeEngine(SimRollingEngine(max_slots=2, steps_per_call=2,
                                        step_s=0.001), poll_s=0.002)
    try:
        with pytest.raises(ValueError, match="no adapter pool"):
            list(eng.generate({"prompt": [1], "max_new_tokens": 2,
                               "adapter": "tenant-a"}))
    finally:
        eng.close()


def test_residency_miss_sheds_typed_then_serves():
    eng, sim, pool = _mk_engine(load_delay=0.05)
    try:
        from kubetorch_tpu.observability import prometheus as prom

        tok_key = prom.adapter_series("tenant-a", "tokens_total")
        shed_key = prom.adapter_series("tenant-a", "sheds_total")
        toks0 = prom.adapter_metrics().get(tok_key, 0.0)
        sheds0 = prom.adapter_metrics().get(shed_key, 0.0)
        prompt = [1, 2, 3]
        prog = {"prompt": prompt, "max_new_tokens": 8,
                "adapter": "tenant-a"}
        # cold adapter: the FIRST submit sheds typed with a Retry-After
        # from the pool's load-time EMA — it never blocks the driver
        with pytest.raises(ServerOverloaded) as err:
            list(eng.generate(prog))
        assert err.value.retry_after and err.value.retry_after > 0
        frames = _until_resident(lambda: list(eng.generate(prog)))
        toks = [t for f in frames for t in f["tokens"]]
        assert toks == SimRollingEngine.expected_tokens(prompt, 8)
        st = eng.stats()
        assert st["adapter_resident"] == 1
        assert st["adapter_loads"] == 1
        assert st["adapter_misses"] >= 1
        assert st["adapter_slots"] == 2
        # per-tenant telemetry: tokens + sheds landed in the NAME-keyed
        # dynamic families, TTFT in the per-adapter histogram
        m = prom.adapter_metrics()
        assert m[tok_key] - toks0 == len(toks)
        assert m[shed_key] - sheds0 >= 1
        assert any(k.startswith("engine_adapter__tenant_a_ttft_seconds")
                   for k in prom.hist_metrics())
        # ... and the fleet plane carries them (engine_ frame prefix)
        from kubetorch_tpu.observability.fleetstore import build_frame

        frame = build_frame(prom.adapter_metrics(), {}, last_sent={},
                            full=True)
        assert frame["m"].get(tok_key) == m[tok_key]
    finally:
        eng.close()


def test_prefix_entries_die_with_adapter_eviction():
    """Satellite regression: prefix KV is keyed by adapter NAME. With
    one pool slot, tenant-b displaces tenant-a; tenant-a's cached
    prefix must not survive into the recycled slot — neither serving
    tenant-b (cross-tenant KV) nor a reloaded tenant-a (stale epoch)."""
    eng, sim, pool = _mk_engine(pool_slots=1, sim_slots=2)
    try:
        tokens = [5, 6, 7, 8]
        pid_a = _until_resident(
            lambda: eng.register_prefix(tokens, adapter="tenant-a"))
        # idempotent re-register: same NAME + tokens → cached pid
        assert eng.register_prefix(tokens, adapter="tenant-a") == pid_a
        fill_a = sim.prefill_tokens
        # tenant-b displaces tenant-a from the single slot
        pid_b = _until_resident(
            lambda: eng.register_prefix(tokens, adapter="tenant-b"))
        assert pool.resident() == {"tenant-b": 0}
        assert pid_b != pid_a, "tenant-b served tenant-a's prefix KV"
        assert sim.prefill_tokens > fill_a, \
            "tenant-b's prefix was never prefilled under its own weights"
        # a reloaded tenant-a re-fills too — its old entry died with
        # the eviction (the slot's device KV now holds other weights)
        fill_b = sim.prefill_tokens
        pid_a2 = _until_resident(
            lambda: eng.register_prefix(tokens, adapter="tenant-a"))
        assert pid_a2 != pid_a
        assert sim.prefill_tokens > fill_b
        assert eng.stats()["adapter_evictions"] >= 2
    finally:
        eng.close()


def test_park_evict_adapter_resume_byte_identical(local_store):
    """Satellite: export/import carries the adapter NAME binding. A
    session parks under tenant-a, tenant-a is LRU-evicted (slot
    recycled to tenant-b), and the resume — naming tenant-a — first
    sheds typed (non-resident ⇒ pool load), then continues the token
    stream byte-identical once the reload lands."""
    prompt = [3, 1, 4, 1, 5]
    n = 120
    expected = SimRollingEngine.expected_tokens(prompt, n)
    eng, sim, pool = _mk_engine(pool_slots=1, sim_slots=2, step_s=0.01)
    try:
        prog = {"prompt": prompt, "max_new_tokens": n,
                "session_id": "sess-lora", "adapter": "tenant-a"}
        first_half: list = []
        parked = threading.Event()

        def run_first():
            # the shed surfaces on iteration (generate() is lazy), and
            # only at admission — before any token lands
            deadline = time.time() + 15
            while True:
                try:
                    for f in eng.generate(prog):
                        if f.get("parked"):
                            parked.set()
                            return
                        first_half.extend(f["tokens"])
                    return
                except ServerOverloaded:
                    assert time.time() < deadline
                    time.sleep(0.01)

        th = threading.Thread(target=run_first)
        th.start()
        _wait(lambda: first_half, what="tokens before park")
        assert eng.park("sess-lora") == 1
        th.join(10)
        assert parked.is_set()
        assert 0 < len(first_half) < n
        # the parked row released its pin: tenant-b can now displace
        # tenant-a from the single slot
        _until_resident(lambda: list(eng.generate(
            {"prompt": [9, 9], "max_new_tokens": 4,
             "adapter": "tenant-b"})))
        assert pool.resident() == {"tenant-b": 0}
        # resume under the WRONG name refuses — the binding rode the blob
        with pytest.raises(ValueError, match="fixed at park"):
            list(eng.generate({**prog, "adapter": "tenant-b"}))
        # resume under tenant-a: sheds while cold, then continues the
        # stream byte-identical (no re-prefill — restore, not replay)
        prefill_before = sim.prefill_tokens
        frames = _until_resident(lambda: list(eng.generate(prog)))
        second_half = [t for f in frames for t in f["tokens"]]
        assert frames[-1]["done"]
        assert first_half + second_half == expected
        assert sim.prefill_tokens == prefill_before, \
            "resume re-ran prompt prefill"
        assert pool.resident() == {"tenant-a": 0}
    finally:
        eng.close()


def test_cross_pod_handoff_adapter_binding(local_store):
    """ISSUE 17 satellite: an ``adapter=name`` program prefilled on the
    prefill tier hands its row off WITH the name binding — the decode
    pod must trigger/await that adapter's residency before the import
    (typed shed + background load, never a blocking fetch), resolve
    the slot exactly once at the splice (no mid-decode slot rewrite),
    refuse a resume under any other name, and stream byte-identical
    with no re-prefill."""
    prompt = [2, 7, 1, 8]
    n = 24
    hid = "h-lora-xpod"
    expected = SimRollingEngine.expected_tokens(prompt, n)
    # prefill pod — cold too: the first submit sheds until tenant-a
    # residency lands, then prefills and exports under the name
    sim_pf = SimRollingEngine(max_slots=2, steps_per_call=4,
                              step_s=0.001, adapter_slots=2)
    pool_pf = AdapterPool(2, lambda name: {"adapter": name},
                          sim_pf.load_adapter_slot,
                          load_ema_alpha=0.5, load_seed_s=0.1)
    pf = DecodeEngine(sim_pf, poll_s=0.002, adapter_pool=pool_pf,
                      phase="prefill")
    # decode pod — same geometry (adapter_slots is the lora_slots
    # geometry axis) but cold for tenant-a; device slot writes are
    # counted so "no mid-decode rewrite" is assertable
    sim_dc = SimRollingEngine(max_slots=2, steps_per_call=4,
                              step_s=0.001, adapter_slots=2)
    writes: list = []

    def counted_write(slot, tree):
        writes.append(int(slot))
        sim_dc.load_adapter_slot(slot, tree)

    pool_dc = AdapterPool(2, lambda name: {"adapter": name},
                          counted_write,
                          load_ema_alpha=0.5, load_seed_s=0.1)
    dc = DecodeEngine(sim_dc, poll_s=0.002, adapter_pool=pool_dc,
                      phase="decode")
    try:
        frames = _until_resident(lambda: list(pf.generate(
            {"prompt": prompt, "max_new_tokens": n,
             "adapter": "tenant-a", "handoff": {"id": hid}})))
        assert frames[-1]["handoff_id"] == hid
        assert all(f["tokens"] == [] for f in frames)
        assert sim_pf.prefill_tokens == len(prompt)
        # resume under the WRONG name refuses — the binding rode the
        # blob (and the refusal leaves the blob importable)
        prog_dc = {"prompt": prompt, "max_new_tokens": n,
                   "handoff_id": hid, "adapter": "tenant-a"}
        with pytest.raises(ValueError, match="fixed at export"):
            list(dc.generate({**prog_dc, "adapter": "tenant-b"}))
        # cold decode pod: the import sheds typed UNTIL residency —
        # the splice must never run ahead of the adapter
        with pytest.raises(ServerOverloaded) as err:
            list(dc.generate(prog_dc))
        assert err.value.retry_after and err.value.retry_after > 0
        assert not writes or pool_dc.resident()  # load in flight
        frames = _until_resident(lambda: list(dc.generate(prog_dc)))
        toks = [t for f in frames for t in f["tokens"]]
        assert toks == expected
        assert sim_dc.prefill_tokens == 0, "decode pod re-ran prefill"
        # residency was installed ONCE, before the import, and the
        # slot never rewrote mid-decode
        assert writes == [pool_dc.resident()["tenant-a"]]
        assert dc.stats()["handoff_imports"] == 1
    finally:
        pf.close()
        dc.close()


def test_adapter_pin_survives_lru_pressure():
    """A decoding row pins its adapter: staged loads must WAIT rather
    than evict it mid-stream, and the pin releases with the row."""
    eng, sim, pool = _mk_engine(pool_slots=1, sim_slots=2, step_s=0.01)
    try:
        _until_resident(lambda: list(eng.generate(
            {"prompt": [1], "max_new_tokens": 2, "adapter": "tenant-a"})))
        holder = {}

        def start_stream():
            g = eng.generate({"prompt": [2, 2], "max_new_tokens": 4000,
                              "adapter": "tenant-a"})
            first = next(g)             # sheds surface on iteration
            holder["gen"] = g
            return first

        assert _until_resident(start_stream)["tokens"]
        with pytest.raises(ServerOverloaded):
            list(eng.generate({"prompt": [3], "max_new_tokens": 2,
                               "adapter": "tenant-b"}))
        # the fetch finishes but cannot place: tenant-a stays resident
        _wait(lambda: pool.stats()["staged"] == 1, what="staged tenant-b")
        time.sleep(0.05)                # a few ticks of admit_ready
        assert pool.resident() == {"tenant-a": 0}
        assert eng.stats()["adapter_pinned"] == 1
        holder["gen"].close()
    finally:
        eng.close()


# ------------------------------------- real model (jax) identity
@pytest.fixture(scope="module")
def rmodel():
    import jax

    from kubetorch_tpu.models import LlamaConfig, llama

    cfg = LlamaConfig(vocab_size=256, embed_dim=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, head_dim=16, mlp_dim=128, remat=False,
                      dtype="float32", param_dtype="float32",
                      max_seq_len=128)
    return llama.init(jax.random.key(0), cfg), cfg


@pytest.mark.level("minimal")
def test_real_model_dynamic_pool_matches_frozen_engine(rmodel, local_store):
    """Acceptance: a program decoded under adapter k through the DYNAMIC
    pool (empty at ctor; named adapters hot-loaded into fixed slots)
    streams byte-identical to the same program on a ctor-FROZEN stacked
    engine — including through a prefix hit and a park/resume
    (mid-stream partition through the store). The pool's per-slot
    dynamic-slice write plus the gather select must be invisible in the
    tokens; only residency timing (the typed sheds) may differ."""
    import jax
    import jax.numpy as jnp

    from kubetorch_tpu.models import lora as lora_mod
    from kubetorch_tpu.models.lora import LoraConfig, stack_adapters
    from kubetorch_tpu.models.rolling import RollingGenerator

    params, cfg = rmodel
    lcfg = LoraConfig(rank=4, alpha=8.0)

    def noisy(key):
        ad = lora_mod.init(key, params, lcfg)
        ks = jax.random.split(key, len(ad))
        for k, name in zip(ks, sorted(ad)):
            ad[name]["b"] = (jax.random.normal(
                k, ad[name]["b"].shape, jnp.float32) * 0.2
            ).astype(ad[name]["b"].dtype)
        return ad

    ads = {"tenant-a": noisy(jax.random.key(40)),
           "tenant-b": noisy(jax.random.key(41))}

    # ground truth: both adapters baked in at construction, addressed
    # by raw slot int — the pre-pool serving path
    frozen = RollingGenerator(
        params, cfg, max_slots=2, max_len=96, steps_per_call=4,
        adapters=stack_adapters([ads["tenant-a"], ads["tenant-b"]], lcfg),
        adapter_scale=lcfg.scale)
    eng_f = DecodeEngine(frozen, poll_s=0.002)

    # dynamic: ctor sees only a ZERO adapter (zero delta = base model)
    # padded to the fixed slot width; real weights arrive exclusively
    # through the pool's background fetch + load_adapter_slot write
    dyn = RollingGenerator(
        params, cfg, max_slots=2, max_len=96, steps_per_call=4,
        adapters=stack_adapters([lora_mod.init(jax.random.key(9),
                                               params, lcfg)], lcfg),
        adapter_scale=lcfg.scale, lora_slots=2)
    pool = AdapterPool(2, lambda name: stack_adapters([ads[name]], lcfg),
                       dyn.load_adapter_slot,
                       load_ema_alpha=0.5, load_seed_s=0.05)
    eng_d = DecodeEngine(dyn, poll_s=0.002, adapter_pool=pool)

    prompt = [3, 7, 11, 2]
    n = 16
    try:
        def run_f(**kw):
            return [t for f in eng_f.generate(
                {"prompt": prompt, "max_new_tokens": n, **kw})
                for t in f["tokens"]]

        def run_d(name, **kw):
            return _until_resident(lambda: [
                t for f in eng_d.generate(
                    {"prompt": prompt, "max_new_tokens": n,
                     "adapter": name, **kw})
                for t in f["tokens"]])

        expect_a, expect_b = run_f(adapter_id=0), run_f(adapter_id=1)
        base = run_f()
        assert expect_a != base, "adapter 0 never steered the stream"
        # named decode through the pool == frozen slots, per tenant
        assert run_d("tenant-a") == expect_a
        assert run_d("tenant-b") == expect_b
        assert pool.resident() == {"tenant-a": 0, "tenant-b": 1}

        # --- through a prefix hit: registered under the NAME on the
        # dynamic engine, under the raw slot on the frozen one
        prefix = [5, 6, 7, 8, 9, 10]
        suffix = [12, 13]
        full = {"prompt": prefix + suffix, "max_new_tokens": n}
        expect_px = [t for f in eng_f.generate({**full, "adapter_id": 0})
                     for t in f["tokens"]]
        pid_f = eng_f.register_prefix(prefix, adapter_id=0)
        pid_d = _until_resident(
            lambda: eng_d.register_prefix(prefix, adapter="tenant-a"))
        hit_f = [t for f in eng_f.generate(
            {"prompt": suffix, "max_new_tokens": n, "prefix_id": pid_f,
             "adapter_id": 0}) for t in f["tokens"]]
        hit_d = _until_resident(lambda: [t for f in eng_d.generate(
            {"prompt": suffix, "max_new_tokens": n, "prefix_id": pid_d,
             "adapter": "tenant-a"}) for t in f["tokens"]])
        assert hit_f == expect_px, "frozen prefix hit diverged"
        assert hit_d == expect_px, "dynamic-pool prefix hit diverged"

        # --- through a park/resume: partition the stream mid-flight,
        # round-trip the row's KV through the real store, continue
        sid = "sess-real-lora"
        prog = {"prompt": prompt, "max_new_tokens": n,
                "session_id": sid, "adapter": "tenant-a"}

        def start():
            g = eng_d.generate(prog)
            return g, next(g)           # sheds surface on iteration

        g, first = _until_resident(start)
        first_half = list(first["tokens"])
        assert eng_d.park(sid) == 1
        for f in g:
            if f.get("parked"):
                break
            first_half.extend(f["tokens"])
        assert 0 < len(first_half) < n
        frames = _until_resident(lambda: list(eng_d.generate(prog)))
        second_half = [t for f in frames for t in f["tokens"]]
        assert frames[-1]["done"]
        assert first_half + second_half == expect_a
    finally:
        eng_f.close()
        eng_d.close()


# -------------------------------------------- fleet SLO (per tenant)
def test_per_adapter_slo_breaches_independently_of_fleet():
    """Acceptance: a per-adapter SLO objective (selectors over the
    dynamic engine_adapter__<name>_* families) burns and breaches on
    ONE tenant's shed-rate while the fleet-wide objective — the same
    window, the same pods — stays green."""
    from kubetorch_tpu.observability.fleetstore import FleetStore
    from kubetorch_tpu.observability.slo import Objective, SLOEngine

    clock = [0.0]
    store = FleetStore(raw_s=120.0, mid_s=900.0, retain_s=3600.0,
                       stale_after_s=30.0, clock=lambda: clock[0])
    slo = SLOEngine(
        store,
        objectives=[
            Objective(service="svc", name="tenant-a-shed", kind="ratio",
                      bad="engine_adapter__tenant_a_sheds_total",
                      total="engine_adapter__tenant_a_generations_total",
                      objective=0.98, burn_threshold=2.0),
            Objective(service="svc", name="fleet-shed", kind="ratio",
                      bad="engine_sheds_total",
                      total="engine_generations_total",
                      objective=0.98, burn_threshold=2.0),
        ],
        fast_s=30.0, slow_s=30.0, clock=lambda: clock[0])
    slo._started = -3600.0
    for i in range(1, 4):
        clock[0] += 1.0
        store.ingest("svc", "p0", {"ts": clock[0], "m": {
            # tenant-a: 50% of its submissions shed (cold-adapter storm)
            "engine_adapter__tenant_a_generations_total": 20.0 * i,
            "engine_adapter__tenant_a_sheds_total": 10.0 * i,
            # fleet-wide: those 10 sheds drown in 10k generations
            "engine_generations_total": 10000.0 * i,
            "engine_sheds_total": 10.0 * i}})
    by_name = {s["name"]: s for s in slo.evaluate()}
    assert by_name["tenant-a-shed"]["breached"]
    assert by_name["tenant-a-shed"]["burn_rate"] >= 2.0
    assert not by_name["fleet-shed"]["breached"]
    assert by_name["fleet-shed"]["burn_rate"] < 1.0
