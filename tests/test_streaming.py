"""Result streaming over the call path: generator-returning callables
stream framed items to `remote.stream(...)`, drain to a list for plain
calls, rehydrate mid-stream errors, and collect per-rank lists in
distributed mode. (The reference streams logs only, never results — this
exceeds parity for LLM-serving workloads.)"""

import os
import time
from pathlib import Path

import pytest

import kubetorch_tpu as kt
from kubetorch_tpu.resources.callables.fn import Fn

ASSETS = Path(__file__).parent / "assets" / "summer"


@pytest.fixture(autouse=True, scope="module")
def _local_state(tmp_path_factory):
    state = tmp_path_factory.mktemp("ktlocal-stream")
    os.environ["KT_LOCAL_STATE"] = str(state)
    import kubetorch_tpu.provisioning.backend as backend

    backend._LOCAL_ROOT = state
    yield
    for record in backend.LocalBackend().list_services():
        backend.LocalBackend().teardown(record["service_name"], quiet=True)


@pytest.fixture(scope="module")
def streamer():
    remote = Fn(root_path=str(ASSETS), import_path="summer",
                callable_name="count_stream", name="streamer")
    remote.to(kt.Compute(cpus="0.1"))
    yield remote
    remote.teardown()


@pytest.mark.level("minimal")
def test_stream_yields_items(streamer):
    items = list(streamer.stream(4))
    assert items == [{"i": i, "sq": i * i} for i in range(4)]


@pytest.mark.level("minimal")
def test_plain_call_drains_generator(streamer):
    assert streamer(3) == [{"i": i, "sq": i * i} for i in range(3)]


@pytest.mark.level("minimal")
def test_stream_is_progressive(streamer):
    """First item must arrive well before the generator finishes."""
    it = streamer.stream(5, delay=0.4)
    t0 = time.perf_counter()
    first = next(it)
    t_first = time.perf_counter() - t0
    rest = list(it)
    t_all = time.perf_counter() - t0
    assert first == {"i": 0, "sq": 0}
    assert len(rest) == 4
    assert t_first < t_all / 2, (t_first, t_all)


@pytest.mark.level("minimal")
def test_async_generator_streams(streamer):
    remote = Fn(root_path=str(ASSETS), import_path="summer",
                callable_name="count_stream_async", name="astreamer")
    remote.to(kt.Compute(cpus="0.1"))
    try:
        assert list(remote.stream(3)) == [0, 10, 20]
        assert remote(3) == [0, 10, 20]
    finally:
        remote.teardown()


@pytest.mark.level("minimal")
def test_midstream_error_rehydrates(streamer):
    remote = Fn(root_path=str(ASSETS), import_path="summer",
                callable_name="broken_stream", name="brokenstream")
    remote.to(kt.Compute(cpus="0.1"))
    try:
        got = []
        with pytest.raises(ValueError, match="stream blew up"):
            for item in remote.stream(3):
                got.append(item)
        assert got == [0, 1, 2]  # items before the failure were delivered
        # plain call path also surfaces the error
        with pytest.raises(ValueError, match="stream blew up"):
            remote(2)
    finally:
        remote.teardown()


@pytest.mark.level("minimal")
def test_abandoned_stream_frees_worker():
    """Cancel mid-stream (the client-disconnect path): the worker closes
    the generator, the terminal arrives, and the worker keeps serving."""
    from kubetorch_tpu import serialization
    from kubetorch_tpu.serving.process_pool import ProcessPool

    pool = ProcessPool(num_procs=1)
    pool.start()
    try:
        pool.setup_all(root_path=str(ASSETS), import_path="summer",
                       name="count_stream")
        body = serialization.dumps(
            {"args": [10_000], "kwargs": {"delay": 0.01}}, "json")
        resp = pool.call(body, "json", timeout=30)
        stream = resp["stream"]
        it = iter(stream)
        assert next(it)["ok"]
        assert next(it)["ok"]
        stream.cancel()
        # drain to the terminal — must arrive promptly, not after 10k items
        t0 = time.perf_counter()
        remaining = sum(1 for _ in it)
        assert time.perf_counter() - t0 < 10
        assert remaining < 1000
        assert stream.terminal.get("ok")
        # worker still serves
        body2 = serialization.dumps({"args": [2], "kwargs": {}}, "json")
        resp2 = pool.call(body2, "json", timeout=30)
        items = [serialization.loads(c["payload"], c["serialization"])
                 for c in resp2["stream"]]
        assert len(items) == 2
    finally:
        pool.stop()


@pytest.mark.level("minimal")
def test_mixed_serialization_stream():
    """A stream that flips json→pickle mid-way decodes per frame."""
    remote = Fn(root_path=str(ASSETS), import_path="summer",
                callable_name="mixed_stream", name="mixedstream")
    remote.to(kt.Compute(cpus="0.1"))
    try:
        # request json: item 1 stays json, item 2 falls back to pickle —
        # the per-frame serialization byte is what keeps this decodable
        items = list(remote.stream(serialization="json"))
        assert items[0] == {"plain": 1}
        assert items[1] == {1, 2, 3} and isinstance(items[1], set)
    finally:
        remote.teardown()


@pytest.mark.level("minimal")
def test_cli_call_stream(streamer):
    """`ktpu call --stream` prints one JSON line per streamed item."""
    from click.testing import CliRunner

    from kubetorch_tpu.cli import main as cli_main

    result = CliRunner().invoke(
        cli_main, ["call", streamer.service_name, "--args", "[3]",
                   "--stream"])
    assert result.exit_code == 0, result.output
    import json as _json

    lines = [_json.loads(line) for line in
             result.output.strip().splitlines()]
    assert lines == [{"i": i, "sq": i * i} for i in range(3)]


@pytest.mark.level("minimal")
def test_distributed_generator_collects_per_rank():
    """SPMD fan-out: each rank's generator collects into a list, results
    aggregate per rank as usual."""
    remote = Fn(root_path=str(ASSETS), import_path="summer",
                callable_name="count_stream", name="dist-stream")
    compute = kt.Compute(cpus="0.1").distribute(
        "spmd", workers=2, num_procs=1, monitor_members=False)
    remote.to(compute)
    try:
        results = remote(3)
        assert len(results) == 2
        expect = [{"i": i, "sq": i * i} for i in range(3)]
        assert all(r == expect for r in results)
    finally:
        remote.teardown()
