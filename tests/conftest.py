"""Test harness: virtual 8-device CPU mesh + test levels.

Mirrors the reference's leveling system (reference:
``python_client/tests/conftest.py:27-41`` — markers unit|minimal|release|gpu
selected via ``--level``), with the GPU tier replaced by a ``tpu`` tier.
The multi-chip story is *better* than the reference's: JAX's
``xla_force_host_platform_device_count`` fakes an 8-device mesh on CPU, so
every sharding/collective path is exercised in CI without hardware
(SURVEY.md §4 "implication for the TPU build").

Wall-time: the persistent XLA compile cache (below) cuts warm runs from
~13 min to ~8 min; ``-n 4`` (pytest-xdist) overlaps the deployment tests'
real-time waits for ~6 min total. Don't parallelize the ``tpu`` tier —
its tests contend for one physical chip.
"""

import os

# The tpu tier (KT_TPU_TESTS=1 pytest --level tpu) runs on live TPU
# hardware — everything else pins to the virtual 8-device CPU mesh.
_TPU_TIER = os.environ.get("KT_TPU_TESTS") == "1"

if not _TPU_TIER:
    # Must run before any jax import anywhere in the test session.
    os.environ["JAX_PLATFORMS"] = "cpu"  # session env may point at a TPU
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()
# Keep test pods/processes off any real TPU tunnel.
os.environ.setdefault("KT_BACKEND", "local")

# --- concurrency sanitizer (ktsan) -----------------------------------------
# KT_SAN=1 instruments every repo-created lock in THIS process and — via
# the inherited env — in every pod/worker subprocess the tests spawn.
# Each process dumps its lock-order graph into KT_SAN_DIR at exit; the
# session fixture below merges them, unions the static graph, and fails
# the run on any lock-order cycle with a rendered path.
# same truthy set as config.env_bool: pods/workers gate on the typed
# accessor, and a KT_SAN=true session must not end up with instrumented
# subprocesses but no test-process install / no session cycle check
_SAN_ENABLED = os.environ.get("KT_SAN", "").strip().lower() in (
    "1", "true", "yes", "on")
if _SAN_ENABLED:
    import tempfile

    os.environ.setdefault("KT_SAN_DIR",
                          tempfile.mkdtemp(prefix="ktsan-"))
    from kubetorch_tpu.analysis import san as _san_mod

    _san_mod.install()

# A sitecustomize may already have imported jax and pointed it at a TPU
# plugin before this conftest runs; override via the live config too.
import jax  # noqa: E402

if not _TPU_TIER:
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # jax < 0.5: no such option — the XLA_FLAGS host-platform flag set
        # above already forces the 8-device CPU mesh.
        pass
    # Persistent XLA compilation cache: the model/parallel tests are
    # compile-bound (~5 min of the suite is jit compiles of programs that
    # never change between runs). Warm runs hit the cache and the suite
    # fits the ~5-minute budget (VERDICT r1 weak #7). Tests that ASSERT
    # on compile-time stderr (remat warnings) disable it locally.
    _cache = os.environ.get(
        "KT_TEST_XLA_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "ktpu-test-xla"))
    if _cache:
        os.makedirs(_cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

import pytest  # noqa: E402

LEVELS = ["unit", "minimal", "release", "tpu"]


@pytest.fixture(autouse=True)
def _reset_singletons():
    """Make the suite order-independent (VERDICT r3 weak #1).

    Process-level caches survive a test's monkeypatches unwinding: a test
    that sets ``KT_CONFIG_PATH``/``KT_NAMESPACE`` and touches
    ``get_config()`` leaves the cached ``KubetorchConfig`` instance behind,
    and later fake-K8s tests then build manifests against stale config.
    Dropping the caches before AND after every test forces each test to
    re-derive state from the environment it actually set up. All of these
    are cheap lazy caches backed by env/disk — nothing live is torn down.
    """
    import kubetorch_tpu.config as config_mod
    import kubetorch_tpu.provisioning.backend as backend_mod
    from kubetorch_tpu.data_store.client import DataStoreClient

    def _drop():
        with config_mod._lock:
            config_mod._config = None
        backend_mod._backends.clear()
        DataStoreClient._default = None

    _drop()
    yield
    _drop()


@pytest.fixture(autouse=True, scope="session")
def _san_session_check():
    """KT_SAN=1: at session end, merge every process's dynamic report
    with the static lock graph and fail the run on any lock-order
    cycle. Session-fixture teardown (not sessionfinish) so the failure
    carries a normal pytest error + nonzero exit."""
    yield
    if not _SAN_ENABLED:
        return
    from kubetorch_tpu.analysis import san as san_mod

    report = san_mod.session_check(os.environ["KT_SAN_DIR"])
    assert report is None, "\n" + report


# Long-lived singletons a module may legitimately leave behind: the
# shared actor-mesh fan-out pool (one per process by design) and the
# jax compilation-cache writer threads.
_LEAK_ALLOW = ("kt-actor-mesh",)


@pytest.fixture(autouse=True, scope="module")
def _thread_leak_guard(request):
    """No non-daemon thread may survive a test module (KT_SAN_LEAKS=0
    to disable). Catches the leaked-driver/leaked-pusher bug class:
    a forgotten engine driver or log-push executor keeps the whole
    pytest process alive at exit and bleeds CPU into every later
    module. Daemon threads are exempt (they can't hang exit); known
    long-lived singletons are allowlisted by name."""
    from kubetorch_tpu.config import env_bool

    # the typed accessor: KT_SAN_LEAKS is a registered bool knob, so
    # every documented spelling (0/false/no/off) disables the guard
    if not env_bool("KT_SAN_LEAKS"):
        yield
        return
    import threading
    import time as _time

    # hold the Thread OBJECTS, not ids: a pre-existing thread's object
    # can be garbage-collected mid-module and a leaked thread allocated
    # at the recycled address would slip the guard
    before = set(threading.enumerate())
    yield

    def leaked():
        return [t for t in threading.enumerate()
                if t.is_alive() and not t.daemon
                and t not in before
                and t is not threading.main_thread()
                and not any(t.name.startswith(p) for p in _LEAK_ALLOW)]

    # teardown grace: executors and drivers that were just shut down may
    # need a beat to exit
    deadline = _time.time() + 2.0
    cur = leaked()
    while cur and _time.time() < deadline:
        _time.sleep(0.05)
        cur = leaked()
    if cur:
        try:
            from kubetorch_tpu.observability import prometheus as prom

            prom.record_san("thread_leak", len(cur))
        except Exception:
            pass
        names = sorted(t.name for t in cur)
        raise AssertionError(
            f"non-daemon thread(s) leaked by {request.module.__name__}: "
            f"{names} — join/shutdown them in teardown, mark them "
            f"daemon if they are best-effort, or allowlist a known "
            f"singleton in conftest._LEAK_ALLOW (KT_SAN_LEAKS=0 "
            f"disables this guard)")


def pytest_addoption(parser):
    parser.addoption(
        "--level", default="minimal", choices=LEVELS,
        help="run tests at or below this level")


def pytest_configure(config):
    # The env var (pins/unpins the CPU mesh at import time) and the level
    # option must agree — KT_TPU_TESTS=1 without --level tpu would run the
    # ordinary suite on live hardware with no 8-device mesh.
    if _TPU_TIER and config.getoption("--level") != "tpu":
        raise pytest.UsageError(
            "KT_TPU_TESTS=1 requires --level tpu (the tpu tier runs ONLY "
            "the hardware tests)")
    if config.getoption("--level") == "tpu" and not _TPU_TIER:
        raise pytest.UsageError(
            "--level tpu requires KT_TPU_TESTS=1 (set before pytest starts "
            "so the CPU-mesh pin is skipped)")


def pytest_collection_modifyitems(config, items):
    max_level = LEVELS.index(config.getoption("--level"))
    tpu_ix = LEVELS.index("tpu")
    for item in items:
        marker = item.get_closest_marker("level")
        level = LEVELS.index(marker.args[0]) if marker else 0
        if max_level == tpu_ix:
            # The tpu tier runs ONLY hardware tests: lower tiers assume the
            # virtual 8-device CPU mesh, and their subprocess pods would
            # contend for the single libtpu device lock.
            if level != tpu_ix:
                item.add_marker(pytest.mark.skip(
                    reason="tpu tier runs only tpu-level tests"))
        elif level > max_level:
            item.add_marker(
                pytest.mark.skip(reason="needs --level tpu + real TPU")
                if level == tpu_ix else
                pytest.mark.skip(reason=f"needs --level {LEVELS[level]}"))
