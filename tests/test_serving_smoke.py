"""Tier-1-safe serving-bench smoke: ``bench_serving.run(dryrun=True)``
drives the call-tunnel phase (real pod server + worker subprocess +
persistent channel) at toy sizes on CPU, and this test fails if any
``serving_*`` metric KEY disappears — a silently-dropped measurement is
how a perf regression hides (same contract as test_dataplane_smoke)."""

import pytest

# The bench's stable contract: every serving_* key BENCH_r* rounds chart.
# Values are environment-dependent; keys are not. Adding keys is fine;
# losing one fails here first, not in the next bench round's diff.
EXPECTED_KEYS = {
    "serving_pipeline_depth",
    "serving_device_ms_cfg",
    "serving_chunk_tokens",
    "serving_post_ms_p50",
    "serving_chan_ms_p50",
    "serving_chunk_ms_pipelined",
    "serving_chunk_ms_pipelined_spread",
    # per-call latency decomposition (medians over depth-1 channel calls)
    "serving_client_ser_ms",
    "serving_wire_ms",
    "serving_server_queue_ms",
    "serving_worker_dispatch_ms",
    "serving_device_ms",
    # derived: tax above device time + tok/s per tunnel flavor
    "serving_dispatch_tax_ms_post",
    "serving_dispatch_tax_ms_chan",
    "serving_dispatch_tax_ms_pipelined",
    "serving_tok_s_post",
    "serving_tok_s_chan",
    "serving_tok_s_pipelined",
    "serving_pipeline_speedup",
    # distributed tracing rides every call above; its cost is a
    # published number, not an assumption
    "trace_span_count",
    "trace_overhead_us_per_span",
    # engine mode (ISSUE 10): the server-resident generation loop —
    # tunnel-vs-device ratio, amortized per-chunk cost, per-row
    # admission TTFT/goodput, and the scheduler invariants
    "engine_step_ms_cfg",
    "engine_chunk_tokens",
    "engine_tok_s_tunnel_wall",
    "engine_device_tok_s",
    "engine_tunnel_ratio",
    "engine_dispatch_ms_per_chunk",
    "engine_ttft_ms_p50",
    "engine_ttft_ms_p99",
    "engine_poisson_offered_tok_s",
    "engine_poisson_tok_s",
    "engine_poisson_goodput_ratio",
    "engine_prefill_interleave_ok",
    "engine_admit_to_first_token_chunks",
    # flight recorder (ISSUE 19): the per-tick black box must cost well
    # under 1% of a working driver tick
    "flight_overhead_pct",
    # paged KV + prefix cache (ISSUE 11): prefill tokens saved by
    # automatic prefix sharing, and park→resume TTFT in decode chunks
    "prefix_kv_programs",
    "prefix_prefill_tokens_naive",
    "prefix_prefill_tokens_executed",
    "prefix_prefill_tokens_saved_ratio",
    "prefix_kv_hits",
    "prefix_kv_misses",
    "kv_unparked_ttft_ms",
    "kv_park_ms",
    "kv_resume_ttft_ms",
    "kv_resume_ttft_chunks",
    # speculative scheduling (ISSUE 14): per-row adaptive lookahead —
    # paired virtual-time Poisson runs, spec off vs on
    "spec_programs",
    "spec_k_max_cfg",
    "spec_tok_s_off",
    "spec_tok_s_on",
    "spec_goodput_ratio",
    "spec_ttft_ms_p99_off",
    "spec_ttft_ms_p99_on",
    "spec_accept_rate",
    "spec_k_p50",
    "spec_k_p99",
    "spec_k_high_accept_p50",
    "spec_k_adversarial_p50",
    # multi-tenant LoRA (ISSUE 16): device-resident adapter pool with
    # O(1) per-row gather select — tenant fan-out surcharge, cold-load
    # shadowing, and the select's cost versus the slot-axis width
    "lora_adapters",
    "lora_slots_cfg",
    "lora_tok_s_single",
    "lora_tok_s_8_adapters",
    "lora_tok_s_ratio_8_adapters",
    "lora_cold_load_hidden_ratio",
    "lora_select_cost_unit",
    "lora_select_cost_1_slot",
    "lora_select_cost_8_slots",
    "lora_select_overhead_pct",
    # disaggregated prefill/decode (ISSUE 17): equal-chip paired
    # virtual-time overload — tier split + block-granular KV handoff
    # versus the monolithic mixed fleet
    "disagg_programs",
    "disagg_handoff_chunks",
    "disagg_handoff_bytes_p50",
    "disagg_handoff_overlap_ratio",
    "disagg_ttft_p99_ms",
    "disagg_ttft_p99_ms_mono",
    "disagg_ttft_p99_ms_vs_monolithic",
    "disagg_tok_s",
    "disagg_tok_s_mono",
    "disagg_goodput_tok_s",
    "disagg_goodput_tok_s_mono",
    "disagg_goodput_ratio",
    "disagg_decode_mbu_proxy",
    # fleet telemetry plane (ISSUE 13): what the heartbeat piggyback
    # costs and what one SLO evaluation sweep costs
    "telemetry_frames",
    "telemetry_frame_bytes_avg",
    "telemetry_build_us_per_frame",
    "telemetry_ingest_us_per_frame",
    "telemetry_ingest_overhead_pct",
    "slo_eval_ms",
    "slo_objectives",
}


@pytest.mark.level("minimal")
def test_serving_dryrun_metric_keys():
    from kubetorch_tpu import bench_serving

    out = bench_serving.run(dryrun=True)
    missing = EXPECTED_KEYS - set(out)
    assert not missing, (
        f"serving bench dropped metric keys: {sorted(missing)} — a "
        f"measurement went silent; restore it (or update EXPECTED_KEYS "
        f"if the rename is deliberate)")
    # sanity: real measurements, right shapes
    assert out["serving_post_ms_p50"] > 0
    assert out["serving_chan_ms_p50"] > 0
    assert out["serving_chunk_ms_pipelined"] > 0
    assert out["serving_tok_s_pipelined"] > 0
    lo, hi = out["serving_chunk_ms_pipelined_spread"]
    assert lo <= out["serving_chunk_ms_pipelined"] <= hi
    # the simulated device time must show up in the measured device
    # stage (worker-side execution covers the sleep)
    assert out["serving_device_ms"] >= out["serving_device_ms_cfg"]
    # tracing is always-on across the bench's calls (client spans at
    # minimum), and its cost must stay invisible on the pipelined path:
    # a pipelined channel call records 2 client-side spans
    # (channel.call + channel.send) — budget 4 for margin and require
    # their summed overhead under 5% of one pipelined chunk's wall.
    # (This sandbox measures ~13-16 µs/span, so 4 spans ≈ 65 µs against
    # a ~160 µs budget — headroom for a noisy host, while a real
    # regression to ~50 µs/span still fails.)
    assert out["trace_span_count"] >= 1
    per_span_us = out["trace_overhead_us_per_span"]
    assert per_span_us > 0
    chunk_us = out["serving_chunk_ms_pipelined"] * 1000.0
    assert per_span_us * 4 < 0.05 * chunk_us, (
        f"tracing overhead {per_span_us} µs/span × 4 spans/call exceeds "
        f"5% of the {chunk_us:.0f} µs pipelined chunk")
    # engine mode: the scheduler invariants hold on the CPU path, and
    # the server-resident loop's overhead is AMORTIZED fixed cost —
    # per-chunk dispatch well under one chunk's device time (the
    # client-driven loop paid ~144 ms/chunk, ~5x device, in BENCH_r05)
    assert out["engine_prefill_interleave_ok"] == 1.0, (
        "decode stalled during chunked prefill")
    assert out["engine_admit_to_first_token_chunks"] <= 9, (
        "admit-to-first-token unbounded: "
        f"{out['engine_admit_to_first_token_chunks']} ticks for an "
        f"8-chunk prompt")
    assert out["engine_dispatch_ms_per_chunk"] < out["engine_step_ms_cfg"]
    # flight recorder (ISSUE 19): one ring append per driver tick must
    # stay under 1% of a working tick's wall
    assert 0 <= out["flight_overhead_pct"] < 1.0, out["flight_overhead_pct"]
    # CI floor (the full bench asserts the 0.9 acceptance bar itself;
    # a loaded CI host gets headroom)
    assert out["engine_tunnel_ratio"] > 0.5, out["engine_tunnel_ratio"]
    assert out["engine_poisson_goodput_ratio"] > 0.4
    assert out["engine_ttft_ms_p50"] > 0
    assert out["engine_ttft_ms_p99"] >= out["engine_ttft_ms_p50"]
    # paged KV + prefix cache: with an N-way shared prefix, prefill
    # tokens executed grow O(suffix), not O(N·prompt) — the acceptance
    # floor is half of perfect sharing's (N−1)/N
    n = out["prefix_kv_programs"]
    assert out["prefix_prefill_tokens_saved_ratio"] >= \
        0.5 * (n - 1) / n, out["prefix_prefill_tokens_saved_ratio"]
    assert out["prefix_kv_hits"] == n - 1
    assert out["prefix_kv_misses"] == 1
    assert out["prefix_prefill_tokens_executed"] < \
        out["prefix_prefill_tokens_naive"]
    # park → resume: the resumed session's first token costs ~one decode
    # chunk (CI headroom: 4), not the prompt's full chunked prefill
    assert out["kv_resume_ttft_chunks"] <= 4.0, out["kv_resume_ttft_chunks"]
    assert out["kv_resume_ttft_ms"] < 0.5 * out["kv_unparked_ttft_ms"]
    # speculative scheduling (ISSUE 14 acceptance): at the same seeded
    # overload, spec-on goodput beats spec-off at equal-or-better TTFT
    # p99 (virtual-time phase — deterministic, so the floors are tight),
    # and per-row adaptive k converges BOTH directions: high-accept
    # rows hold k > 2, adversarial-random rows settle at k = 1
    assert out["spec_tok_s_on"] >= out["spec_tok_s_off"], (
        out["spec_tok_s_on"], out["spec_tok_s_off"])
    assert out["spec_goodput_ratio"] >= 1.05, out["spec_goodput_ratio"]
    assert out["spec_ttft_ms_p99_on"] <= out["spec_ttft_ms_p99_off"], (
        out["spec_ttft_ms_p99_on"], out["spec_ttft_ms_p99_off"])
    assert out["spec_k_high_accept_p50"] > 2, out["spec_k_high_accept_p50"]
    assert out["spec_k_adversarial_p50"] <= 1.0, (
        out["spec_k_adversarial_p50"])
    assert 0.0 < out["spec_accept_rate"] < 1.0, out["spec_accept_rate"]
    # multi-tenant LoRA (ISSUE 16 acceptance): 8 concurrent tenants
    # deliver >= 0.9x the single-adapter tok/s at the same offered
    # load; a mid-stream cold-load storm steals < 25% of decode wall
    # (fetches run off the driver tick); and the gather select's
    # compiled cost stays flat as the slot axis widens 1 -> 8
    # (bench_lora asserts its own tighter flops bound)
    assert out["lora_tok_s_ratio_8_adapters"] >= 0.9, (
        out["lora_tok_s_ratio_8_adapters"])
    assert out["lora_cold_load_hidden_ratio"] >= 0.75, (
        out["lora_cold_load_hidden_ratio"])
    bound = 1.0 if out["lora_select_cost_unit"] == "flops" else 30.0
    assert out["lora_select_overhead_pct"] < bound, (
        out["lora_select_overhead_pct"], out["lora_select_cost_unit"])
    assert out["lora_tok_s_single"] > 0
    # disaggregated prefill/decode (ISSUE 17 acceptance): at equal chip
    # count the specialized fleet wins BOTH tails — SLO goodput (the
    # monolithic fleet's interleaved prefill inflates inter-token gaps
    # and slot hold times) AND TTFT p99 — with the KV handoff under 3
    # decode chunks of wire latency and genuinely overlapped with the
    # prefill pod's next rows. Virtual-time phase: deterministic, so
    # the floors carry only modest headroom below the measured point.
    assert out["disagg_goodput_ratio"] >= 2.0, out["disagg_goodput_ratio"]
    assert out["disagg_ttft_p99_ms_vs_monolithic"] <= 0.8, (
        out["disagg_ttft_p99_ms_vs_monolithic"])
    assert out["disagg_handoff_chunks"] <= 3.0, out["disagg_handoff_chunks"]
    assert out["disagg_handoff_overlap_ratio"] >= 0.5, (
        out["disagg_handoff_overlap_ratio"])
    assert out["disagg_decode_mbu_proxy"] >= 0.3, (
        out["disagg_decode_mbu_proxy"])
    assert out["disagg_handoff_bytes_p50"] > 0
    assert out["disagg_tok_s"] > 0 and out["disagg_tok_s_mono"] > 0
    # fleet telemetry plane: the heartbeat piggyback (frame build +
    # controller ingest) must stay under 3% of a heartbeat tick, and an
    # SLO evaluation sweep must be cheap enough for the resilience
    # sweep cadence (bench_telemetry also asserts the 3% bound itself)
    assert 0 < out["telemetry_ingest_overhead_pct"] < 3.0, (
        out["telemetry_ingest_overhead_pct"])
    assert out["telemetry_build_us_per_frame"] > 0
    assert 0 < out["slo_eval_ms"] < 250.0, out["slo_eval_ms"]
    assert out["slo_objectives"] >= 1
    # dryrun toy values must never be compared against prior rounds
    assert "rolling_tok_s_tunnel_wall" not in out
