"""Tier-1-safe serving-bench smoke: ``bench_serving.run(dryrun=True)``
drives the call-tunnel phase (real pod server + worker subprocess +
persistent channel) at toy sizes on CPU, and this test fails if any
``serving_*`` metric KEY disappears — a silently-dropped measurement is
how a perf regression hides (same contract as test_dataplane_smoke)."""

import pytest

# The bench's stable contract: every serving_* key BENCH_r* rounds chart.
# Values are environment-dependent; keys are not. Adding keys is fine;
# losing one fails here first, not in the next bench round's diff.
EXPECTED_KEYS = {
    "serving_pipeline_depth",
    "serving_device_ms_cfg",
    "serving_chunk_tokens",
    "serving_post_ms_p50",
    "serving_chan_ms_p50",
    "serving_chunk_ms_pipelined",
    "serving_chunk_ms_pipelined_spread",
    # per-call latency decomposition (medians over depth-1 channel calls)
    "serving_client_ser_ms",
    "serving_wire_ms",
    "serving_server_queue_ms",
    "serving_worker_dispatch_ms",
    "serving_device_ms",
    # derived: tax above device time + tok/s per tunnel flavor
    "serving_dispatch_tax_ms_post",
    "serving_dispatch_tax_ms_chan",
    "serving_dispatch_tax_ms_pipelined",
    "serving_tok_s_post",
    "serving_tok_s_chan",
    "serving_tok_s_pipelined",
    "serving_pipeline_speedup",
}


@pytest.mark.level("minimal")
def test_serving_dryrun_metric_keys():
    from kubetorch_tpu import bench_serving

    out = bench_serving.run(dryrun=True)
    missing = EXPECTED_KEYS - set(out)
    assert not missing, (
        f"serving bench dropped metric keys: {sorted(missing)} — a "
        f"measurement went silent; restore it (or update EXPECTED_KEYS "
        f"if the rename is deliberate)")
    # sanity: real measurements, right shapes
    assert out["serving_post_ms_p50"] > 0
    assert out["serving_chan_ms_p50"] > 0
    assert out["serving_chunk_ms_pipelined"] > 0
    assert out["serving_tok_s_pipelined"] > 0
    lo, hi = out["serving_chunk_ms_pipelined_spread"]
    assert lo <= out["serving_chunk_ms_pipelined"] <= hi
    # the simulated device time must show up in the measured device
    # stage (worker-side execution covers the sleep)
    assert out["serving_device_ms"] >= out["serving_device_ms_cfg"]
    # dryrun toy values must never be compared against prior rounds
    assert "rolling_tok_s_tunnel_wall" not in out
