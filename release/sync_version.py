"""Keep chart + pyproject versions in lockstep with kubetorch_tpu.version
(reference: release/sync_version.py)."""

import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

from kubetorch_tpu.version import __version__  # noqa: E402


def sync_chart():
    chart = ROOT / "charts" / "kubetorch-tpu" / "Chart.yaml"
    text = chart.read_text()
    text = re.sub(r"(?m)^version: .*$", f"version: {__version__}", text)
    text = re.sub(r"(?m)^appVersion: .*$",
                  f'appVersion: "{__version__}"', text)
    chart.write_text(text)


def sync_pyproject():
    py = ROOT / "pyproject.toml"
    text = py.read_text()
    text = re.sub(r'(?m)^version = ".*"$', f'version = "{__version__}"',
                  text)
    py.write_text(text)


if __name__ == "__main__":
    sync_chart()
    sync_pyproject()
    print(f"synced chart + pyproject to {__version__}")
