#!/usr/bin/env bash
# Package + optionally publish the Helm chart (reference: publish_chart.sh).
set -euo pipefail

REPO_URL="${CHART_REPO:-}"   # e.g. oci://ghcr.io/kubetorch-tpu/charts
cd "$(dirname "$0")/.."
python release/sync_version.py   # chart version follows the package
helm package charts/kubetorch-tpu -d dist/
if [[ -n "${REPO_URL}" ]]; then
  helm push dist/kubetorch-tpu-*.tgz "${REPO_URL}"
fi
