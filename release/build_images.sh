#!/usr/bin/env bash
# Build + optionally push the kubetorch_tpu image matrix (reference:
# release/build_images.sh + default_images/ — 5 images there, 5 here):
#   kubetorch-tpu  full stack (pod server + controller + store; the
#                  chart's one image — entrypoint picks the role)
#   server         slim Debian workload base (CPU jax)
#   server-tpu     workload base + jax[tpu]/libtpu
#   server-otel    workload base + OpenTelemetry export
#   ubuntu         Ubuntu workload base (apt ecosystem)
set -euo pipefail

REGISTRY="${REGISTRY:-ghcr.io/kubetorch-tpu}"
PUSH="${PUSH:-0}"
ONLY="${ONLY:-}"

cd "$(dirname "$0")/.."
VERSION="$(python -c 'from kubetorch_tpu.version import __version__; print(__version__)')"

build() {  # name dockerfile [build-args...]
  local name="$1"; shift
  local dockerfile="$1"; shift
  if [[ -n "${ONLY}" && "${ONLY}" != "${name}" ]]; then return; fi
  docker build -f "${dockerfile}" "$@" \
    -t "${REGISTRY}/${name}:${VERSION}" -t "${REGISTRY}/${name}:latest" .
  echo "built ${REGISTRY}/${name}:${VERSION}"
  if [[ "${PUSH}" == "1" ]]; then
    docker push "${REGISTRY}/${name}:${VERSION}"
    docker push "${REGISTRY}/${name}:latest"
  fi
}

build kubetorch-tpu release/Dockerfile
build server release/default_images/server
build ubuntu release/default_images/ubuntu
# variants layer on the freshly-built server base
build server-tpu release/default_images/server-tpu \
  --build-arg "BASE_IMAGE=${REGISTRY}/server:${VERSION}"
build server-otel release/default_images/server-otel \
  --build-arg "BASE_IMAGE=${REGISTRY}/server:${VERSION}"
