#!/usr/bin/env bash
# Build + optionally push the kubetorch_tpu server image.
# (reference: release/build_images.sh — here one image covers server,
# controller, and store: the entrypoint picks the role.)
set -euo pipefail

REGISTRY="${REGISTRY:-ghcr.io/kubetorch-tpu}"
PUSH="${PUSH:-0}"

cd "$(dirname "$0")/.."
VERSION="$(python -c 'from kubetorch_tpu.version import __version__; print(__version__)')"
docker build -f release/Dockerfile -t "${REGISTRY}/kubetorch-tpu:${VERSION}" \
  -t "${REGISTRY}/kubetorch-tpu:latest" .
echo "built ${REGISTRY}/kubetorch-tpu:${VERSION}"
if [[ "${PUSH}" == "1" ]]; then
  docker push "${REGISTRY}/kubetorch-tpu:${VERSION}"
  docker push "${REGISTRY}/kubetorch-tpu:latest"
fi
